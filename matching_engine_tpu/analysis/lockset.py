"""Lockset / thread-role race analyzer (Eraser-style, statically).

PR 9's lock-order analyzer proves declared locks NEST correctly, but says
nothing about shared mutable state touched with no lock at all — the
defect class TSan finds at runtime only on the schedules a test happens
to run. This analyzer closes that gap statically:

1. Every module-level binding and `self.`/typed-receiver attribute
   access in the scanned tree is recorded with the locks lexically held
   at the site (lockorder._Analyzer extracts them as `Access` rows).
2. Thread roles (hierarchy.THREAD_ROLES: gRPC handlers, dispatcher
   drain/lane threads, the async-sink flusher, the audit pump, the feed
   spill flusher, the scrape server, ...) propagate from their entry
   points through the resolvable call graph — the same conservative
   resolution lockorder uses (receiver typing, callback bindings), plus
   parent→closure edges (a closure runs on some caller's thread later;
   it inherits its defining function's roles and NO guaranteed locks).
3. A function's *guaranteed* lockset is computed PER ROLE: the meet
   (intersection) over that role's reachable call sites of (caller's
   guarantee ∪ locks lexically held at the site). `_observe_locked` is
   guaranteed the auditor lock on every role's path because every caller
   holds it — while a boot-path call with no lock only weakens the
   `main` role's guarantee, not the serving threads'.
4. The `main` role (build_server wiring, recovery replay, shutdown) is
   initialization/teardown: it runs before the serving threads spawn or
   after they join, so its accesses are not concurrent with anything —
   exactly Eraser's initialization-phase exemption, role-shaped.
5. For every location with a write outside `__init__`: if two concurrent
   roles reach it and the intersection of the effective locksets
   (per-role guarantee ∪ lexical) over the relevant access instances is
   empty, it is flagged — unless a reviewed hierarchy.OWNERSHIP policy
   covers it, and the policy itself is machine-checked (a
   "single-writer" location acquiring a second writing role becomes
   lockset/ownership-violation, not a silently-wrong waiver).

Also enforced: every `Thread(target=...)` spawn must resolve to a
declared role entry (the role table cannot rot; a dynamic
lambda/partial target is flagged outright — the table can never cover
it), and OWNERSHIP entries that stopped matching any flagged location
are themselves flagged (documented debt cannot accrete) — except
`init-before-spawn` entries, which are declarative: boot-only state
never flags while healthy, and the entry's job is to turn a future
post-boot write into an ownership-violation.

Known approximations (by design, tuned via the tables rather than code):
unresolvable indirect calls don't propagate roles (the guard test in
tests/test_analysis.py pins that the load-bearing state IS seen), and a
closure's guaranteed lockset is empty even when every caller invokes it
under a lock — a false positive there earns an OWNERSHIP entry with a
witness, which is exactly the reviewed-documentation outcome we want.
"""

from __future__ import annotations

from matching_engine_tpu.analysis import hierarchy, lockorder
from matching_engine_tpu.analysis.common import Violation, load_sources
from matching_engine_tpu.analysis.lockorder import FuncInfo, Graph, level_of

# The lock-order scan surface plus the observability layer (the scrape /
# trace / flight-dump threads touch state the serving threads write).
SCAN_DIRS = lockorder.SCAN_DIRS + ("utils/obs.py",)

# Roles that never run concurrently with the serving threads: boot
# wiring/recovery happens before the spawns, shutdown after the joins.
NON_CONCURRENT_ROLES = frozenset({"main"})

# Constructors whose objects are internally synchronized (or immutable):
# accesses THROUGH them are not shared-state races. itertools.count is
# included deliberately: next() on it is a single C call, atomic under
# the GIL, and NativeRingDispatcher._tag_seq relies on exactly that.
SAFE_CTORS = frozenset({
    "queue.Queue", "Queue", "queue.SimpleQueue",
    "threading.Event", "Event", "threading.Lock", "Lock",
    "threading.RLock", "RLock", "threading.Condition", "Condition",
    "threading.Semaphore", "threading.local",
    "itertools.count", "Metrics",
})

_POLICIES = ("single-writer", "init-before-spawn", "gil-atomic",
             "instance-confined")


def _entry_matches(f: FuncInfo, entry: str) -> bool:
    owner, _, name = entry.partition(".")
    if name == "*":
        # Glob = the class's PUBLIC surface (what grpc/http dispatches
        # into); private helpers are reached through calls, under
        # whatever locks the handlers hold.
        return f.cls == owner and not f.name.startswith("_")
    if f.cls == owner and f.name == name:
        return True
    return (f.cls is None and f.name == name
            and f.module.rsplit(".", 1)[-1] == owner)


def _ident_declared(ident: str) -> bool:
    """Does a Thread-target identity ("Cls.meth" | "mod.fn") match any
    declared role entry?"""
    for entries in hierarchy.THREAD_ROLES.values():
        for entry in entries:
            owner, _, name = entry.partition(".")
            iowner, _, iname = ident.partition(".")
            if iowner != owner:
                continue
            # The glob covers exactly what _entry_matches propagates
            # roles into — the class's PUBLIC surface. A spawn onto a
            # private method would pass the root check yet never be
            # race-checked, so it must NOT count as declared.
            if name == iname or (name == "*"
                                 and not iname.startswith("_")):
                return True
    return False


def _levels(lock_ids) -> frozenset[str]:
    return frozenset(level_of(i) for i in lock_ids)


def compute_role_context(graph: Graph):
    """For each role: {qualname -> guaranteed lock levels} over every
    function that role's threads can reach. Reachability and the
    guarantee are computed together: the guarantee of a function is the
    meet over all of the role's call paths into it of (caller guarantee
    ∪ locks lexically held at the call site); closures are reached from
    their defining function but run later, lock-free."""
    out: dict[str, dict[str, frozenset]] = {}
    for role, entries in hierarchy.THREAD_ROLES.items():
        ctx: dict[str, frozenset] = {}
        for f in graph.funcs.values():
            if any(_entry_matches(f, e) for e in entries):
                ctx[f.qualname] = frozenset()
        changed = True
        while changed:
            changed = False
            for qual in list(ctx):
                f = graph.funcs[qual]
                g = ctx[qual]
                for call in f.calls:
                    incoming = g | _levels(call.held)
                    for callee in graph.resolve(f, call,
                                                skip_generic=True):
                        cq = callee.qualname
                        prev = ctx.get(cq)
                        new = incoming if prev is None else prev & incoming
                        if new != prev:
                            ctx[cq] = frozenset(new)
                            changed = True
                for cq in f.closures:
                    if ctx.get(cq) != frozenset():
                        ctx[cq] = frozenset()
                        changed = True
        out[role] = ctx
    return out


def _location(graph: Graph, state: str) -> str:
    owner, _, attr = state.rpartition(".")
    short = owner.rsplit(".", 1)[-1]
    if short in graph.bases:
        return f"{graph.root_class(short)}.{attr}"
    return state


def collect_locations(graph: Graph):
    """location -> list of access instances
    (kind, role, lockset, where, func). One instance per (access, role)
    pair: the same site reached by two roles contributes each role's own
    guaranteed lockset. Accesses in unreachable functions and in
    `__init__` (initialization happens-before publication of self) are
    excluded; NON_CONCURRENT_ROLES never produce instances."""
    contexts = compute_role_context(graph)
    out: dict[str, list[tuple]] = {}
    for qual, f in graph.funcs.items():
        if f.name == "__init__":
            continue
        for role, ctx in contexts.items():
            if role in NON_CONCURRENT_ROLES or qual not in ctx:
                continue
            base = ctx[qual]
            for a in f.accesses:
                loc = _location(graph, a.state)
                ctor = graph.attr_ctors.get(a.state) \
                    or graph.attr_ctors.get(loc)
                if ctor in SAFE_CTORS:
                    continue
                out.setdefault(loc, []).append(
                    (a.kind, role, base | _levels(a.held), a.where, qual))
    return out


def check(graph: Graph) -> list[Violation]:
    vs: list[Violation] = []
    locations = collect_locations(graph)

    flagged: set[str] = set()     # pre-waiver, for the unused-entry rule
    for loc in sorted(locations):
        instances = locations[loc]
        writes = [a for a in instances if a[0] == "write"]
        if not writes:
            continue
        wroles = {a[1] for a in writes}
        aroles = {a[1] for a in instances}
        if len(aroles) < 2:
            continue
        policy, _witness = hierarchy.OWNERSHIP.get(loc, (None, None))

        if len(wroles) >= 2:
            inter = frozenset.intersection(*(a[2] for a in writes))
            if not inter:
                flagged.add(loc)
                if policy in ("gil-atomic", "instance-confined"):
                    continue
                if policy in ("single-writer", "init-before-spawn"):
                    vs.append(Violation(
                        "lockset/ownership-violation",
                        min(a[3] for a in writes),
                        f"'{loc}' is declared {policy} but roles "
                        f"{sorted(wroles)} all write it — the ownership "
                        f"entry no longer holds"))
                else:
                    vs.append(Violation(
                        "lockset/unguarded-write",
                        min(a[3] for a in writes),
                        f"'{loc}' written by roles {sorted(wroles)} with "
                        f"empty lockset intersection — guard it with one "
                        f"lock or declare ownership in "
                        f"analysis/hierarchy.py"))
                continue
            # Writers share a lock — but a read-only role outside the
            # writers' lock discipline still races (torn/stale read).
            # Fall through to the foreign-read check below.
        # A race also needs a reader (or second writer, handled above)
        # on a thread outside the writing roles.
        foreign_reads = [a for a in instances
                         if a[0] == "read" and a[1] not in wroles]
        if not foreign_reads:
            continue
        inter = frozenset.intersection(
            *(a[2] for a in writes + foreign_reads))
        if inter:
            continue
        flagged.add(loc)
        if policy in ("gil-atomic", "instance-confined"):
            continue
        if policy == "single-writer" and len(wroles) == 1:
            continue
        if policy in ("single-writer", "init-before-spawn"):
            vs.append(Violation(
                "lockset/ownership-violation",
                min(a[3] for a in writes),
                f"'{loc}' is declared {policy} but roles "
                f"{sorted(wroles)} write it — the ownership entry no "
                f"longer holds"))
            continue
        vs.append(Violation(
            "lockset/unguarded-read",
            min(a[3] for a in writes + foreign_reads),
            f"'{loc}' written by role(s) {sorted(wroles)} and read by "
            f"{sorted({a[1] for a in foreign_reads})} with no common "
            f"lock — lock it or declare single-writer/gil-atomic "
            f"ownership in analysis/hierarchy.py"))

    for loc in sorted(hierarchy.OWNERSHIP):
        policy = hierarchy.OWNERSHIP[loc][0]
        if policy not in _POLICIES:
            vs.append(Violation(
                "lockset/unknown-policy",
                f"hierarchy.py OWNERSHIP[{loc!r}]",
                f"unknown ownership policy {policy!r} (expected one of "
                f"{', '.join(_POLICIES)})"))
        elif loc not in flagged and policy != "init-before-spawn":
            # init-before-spawn is DECLARATIVE: boot-only-written state
            # never produces flaggable instances (main is the
            # non-concurrent role), so "nothing flagged" is its healthy
            # steady state, not staleness — the entry exists to turn a
            # future post-boot write into ownership-violation.
            vs.append(Violation(
                "lockset/unused-ownership",
                f"hierarchy.py OWNERSHIP[{loc!r}]",
                "entry no longer matches any cross-thread unguarded "
                "location — delete it (stale waivers hide future races)"))

    for ident, where in sorted(graph.thread_targets):
        if ident == "<dynamic>":
            vs.append(Violation(
                "lockset/undeclared-thread-root", where,
                "Thread target is a dynamic callable (lambda/partial/"
                "computed) — the role table can never cover it; spawn "
                "a named method or function instead"))
        elif not _ident_declared(ident):
            vs.append(Violation(
                "lockset/undeclared-thread-root", where,
                f"Thread(target={ident}) is not covered by any "
                f"hierarchy.THREAD_ROLES entry — declare the role so "
                f"its reachable state is race-checked"))
    return vs


def build_graph() -> Graph:
    return Graph(load_sources(SCAN_DIRS))


def run() -> list[Violation]:
    return check(build_graph())
