"""Lock-order analyzer: extract the lock acquisition graph from every
`with <lock>` / `.acquire()` site across the serving stack and check it
against the declared hierarchy (analysis/hierarchy.py).

How it works (pure AST, no imports of the code under analysis):

1. Every function/method in the scanned modules is summarized: which
   locks it acquires lexically, which side effects it performs (sqlite
   calls, pb2 proto construction), and every call it makes together
   with the locks held at that call site.
2. Calls resolve conservatively: `self.x()` through the enclosing class
   (and analyzed bases), `obj.x()` through the receiver-type table
   (hierarchy.ATTR_TYPES), callbacks through the declared bindings
   (hierarchy.CALLBACK_BINDINGS), and otherwise by method name across
   all analyzed classes — over-approximation by design: a spurious
   resolution is tuned away in ATTR_TYPES, a missed one would hide a
   deadlock.
3. Summaries propagate to a fixpoint, yielding the transitive
   "acquires" and "effects" sets per function and an edge set
   holder-lock -> acquired-lock with a witness chain per edge.
4. The edge set is checked against hierarchy.ORDER (inversions,
   undeclared nestings, re-acquisition of a held lock, cycles) and
   hierarchy.FORBIDDEN_UNDER (sqlite / proto materialization reachable
   under the hub or snapshot lock). `.acquire()` calls outside a
   try/finally-released discipline are flagged wholesale.

The same machinery renders docs/CONCURRENCY.md (see render.py).
"""

from __future__ import annotations

import ast
import dataclasses

from matching_engine_tpu.analysis import hierarchy
from matching_engine_tpu.analysis.common import (
    Source,
    Violation,
    call_name,
    dotted,
    load_sources,
    receiver_name,
    site,
)

# Scanned surface: the concurrency-bearing layers. utils/checkpoint.py
# rides along because it quiesces the dispatch lock from outside server/.
SCAN_DIRS = ("server", "feed", "audit", "storage", "native",
             "replication", "utils/checkpoint.py")

_SQLITE_RECEIVERS = frozenset(
    a for a, t in hierarchy.ATTR_TYPES.items() if t == "sqlite3")
_SQLITE_METHODS = frozenset({
    "execute", "executemany", "executescript", "commit", "cursor",
    "fetchone", "fetchall", "fetchmany", "rollback",
})

_ID_TO_LEVEL: dict[str, str] = {
    ident: level
    for level, idents in hierarchy.LEVELS.items()
    for ident in idents
}

_DECLARED = frozenset(hierarchy.LEVELS)


def _order_closure() -> frozenset[tuple[str, str]]:
    edges = set(hierarchy.ORDER)
    changed = True
    while changed:
        changed = False
        for a, b in list(edges):
            for c, d in list(edges):
                if b == c and (a, d) not in edges:
                    edges.add((a, d))
                    changed = True
    return frozenset(edges)


_CLOSURE = _order_closure()


@dataclasses.dataclass
class CallSite:
    name: str
    recv: str | None
    held: tuple[str, ...]   # lock identities held at the call
    where: str


@dataclasses.dataclass(frozen=True)
class Access:
    """One shared-state touch: a read or mutation of a module-level name
    or an object attribute, with the locks lexically held at the site.
    Consumed by the lockset analyzer (analysis/lockset.py)."""

    state: str              # "Class.attr" | "module.name"
    kind: str               # "read" | "write"
    held: tuple[str, ...]   # lock identities lexically held
    where: str


@dataclasses.dataclass
class FuncInfo:
    qualname: str           # module.Class.method | module.func
    module: str
    cls: str | None
    name: str
    # lexical facts
    acquires: dict[str, str] = dataclasses.field(default_factory=dict)
    effects: dict[str, str] = dataclasses.field(default_factory=dict)
    calls: list[CallSite] = dataclasses.field(default_factory=list)
    edges: list[tuple[str, str, str]] = dataclasses.field(
        default_factory=list)  # (holder_id, lock_id, witness)
    bare_acquires: list[tuple[str, str]] = dataclasses.field(
        default_factory=list)
    accesses: list[Access] = dataclasses.field(default_factory=list)
    closures: list[str] = dataclasses.field(default_factory=list)
    # AST back-references (the determinism taint pass re-walks bodies).
    node: object = None
    src: object = None
    # fixpoint summaries: lock/effect -> witness chain
    trans_acquires: dict[str, str] = dataclasses.field(default_factory=dict)
    trans_effects: dict[str, str] = dataclasses.field(default_factory=dict)


def _is_lockish(attr: str) -> bool:
    return "lock" in attr.lower()


# Container-mutating method names: `self.x.append(v)` is a WRITE to the
# shared state behind `x` even though the binding never changes. put/get
# are deliberately absent (queue.Queue is internally synchronized).
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "add", "discard",
    "remove", "pop", "popleft", "clear", "update", "setdefault",
    "move_to_end", "popitem", "sort", "reverse",
})

# Method names too generic for unknown-receiver fan-out resolution: a
# bare-local `rows.append(...)` must not resolve into every analyzed
# class that happens to define `append`.
_GENERIC_METHODS = _MUTATORS | frozenset({
    "get", "put", "get_nowait", "put_nowait", "join", "wait", "set",
    "is_set", "items", "keys", "values", "copy", "count", "index",
    # flush/close/start exist on file objects, threads, servers AND half
    # the analyzed classes — production call sites go through typed
    # receivers (ATTR_TYPES), so the name fan-out would only add noise.
    "flush", "close", "start",
})


class _Analyzer(ast.NodeVisitor):
    """Extracts FuncInfo for every def in one module."""

    def __init__(self, src: Source):
        self.src = src
        self.module = src.modname
        self.cls: str | None = None
        self.fn: FuncInfo | None = None
        self.held: list[str] = []
        self.funcs: list[FuncInfo] = []
        self.classes: dict[str, list[str]] = {}   # class -> base names
        # Locks released in an enclosing `finally:` — an .acquire()
        # covered by one is disciplined, everything else is bare.
        self.finally_released: list[set[str]] = []
        # Call-node ids of acquire-then-try disciplined acquires
        # (computed per function def).
        self.exempt_acquires: set[int] = set()
        # `from pkg.mod import name [as alias]` bindings (module and
        # function scope alike): alias -> (full module path, name), so
        # bare-name calls to imported functions resolve cross-module.
        self.imports: dict[str, tuple[str, str]] = {}
        # Names bound to pb2 message classes (`OU = pb2.OrderUpdate`):
        # calling one IS proto materialization.
        self.proto_aliases: set[str] = set()
        # Module-level mutable bindings: mutations through them inside
        # functions are shared-state writes (lockset analyzer).
        self.module_globals: set[str] = set()
        # "Class.attr" -> constructor dotted name for `self.x = Ctor()`
        # assignments (any method): lets the lockset analyzer exempt
        # internally-synchronized containers (queue.Queue, Event, ...).
        self.attr_ctors: dict[str, str] = {}
        # Thread entry points spawned in this module:
        # (resolved target "Cls.meth"|"mod.fn", site).
        self.thread_targets: list[tuple[str, str]] = []
        # Names declared `global` in the current function.
        self._global_decls: set[str] = set()
        for n in src.tree.body:
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        self.module_globals.add(t.id)
            elif isinstance(n, ast.AnnAssign) and \
                    isinstance(n.target, ast.Name):
                self.module_globals.add(n.target.id)
        for n in ast.walk(src.tree):
            if isinstance(n, ast.ImportFrom) and n.module:
                for a in n.names:
                    self.imports[a.asname or a.name] = (n.module, a.name)
            elif isinstance(n, ast.Assign):
                d = dotted(n.value)
                if d and d.startswith("pb2."):
                    for t in n.targets:
                        if isinstance(t, ast.Name):
                            self.proto_aliases.add(t.id)

    # -- identity helpers --------------------------------------------------

    def _lock_id(self, node: ast.expr) -> str | None:
        """Map a lock expression to its identity, or None if the
        expression is not lock-like."""
        if isinstance(node, ast.Name):
            if _is_lockish(node.id):
                return f"{self.module}.{node.id}"
            return None
        if not isinstance(node, ast.Attribute) or not _is_lockish(node.attr):
            return None
        base = node.value
        if isinstance(base, ast.Name) and base.id == "self":
            owner = self.cls or self.module
        elif isinstance(base, ast.Name):
            owner = hierarchy.ATTR_TYPES.get(base.id) or f"?{base.id}"
        elif isinstance(base, ast.Attribute):
            owner = hierarchy.ATTR_TYPES.get(base.attr) or f"?{base.attr}"
        else:
            owner = "?"
        return f"{owner}.{node.attr}"

    def _is_sqlite_cm(self, node: ast.expr) -> bool:
        """`with self._conn:` — a transaction context manager."""
        if isinstance(node, ast.Attribute):
            return node.attr in _SQLITE_RECEIVERS
        if isinstance(node, ast.Name):
            return node.id in _SQLITE_RECEIVERS
        return False

    def _state_id(self, node: ast.expr) -> str | None:
        """Shared-state identity for an attribute / module-global
        expression, or None when the receiver is unknown or external.
        Lock objects are excluded — they ARE the synchronization, not
        state it protects."""
        if isinstance(node, ast.Name):
            if node.id in self.module_globals \
                    and not _is_lockish(node.id):
                return f"{self.module}.{node.id}"
            return None
        if not isinstance(node, ast.Attribute) or _is_lockish(node.attr):
            return None
        base = node.value
        if isinstance(base, ast.Name) and base.id == "self":
            if self.cls is None:
                return None
            return f"{self.cls}.{node.attr}"
        if isinstance(base, ast.Name) and base.id in hierarchy.ATTR_TYPES:
            t = hierarchy.ATTR_TYPES[base.id]
            if t is None or t == "sqlite3":
                return None
            return f"{t}.{node.attr}"
        if isinstance(base, ast.Attribute) \
                and base.attr in hierarchy.ATTR_TYPES:
            t = hierarchy.ATTR_TYPES[base.attr]
            if t is None or t == "sqlite3":
                return None
            return f"{t}.{node.attr}"
        return None

    def _access(self, node: ast.expr, kind: str) -> None:
        sid = self._state_id(node)
        if sid is not None and self.fn is not None:
            self.fn.accesses.append(Access(
                sid, kind, tuple(self.held), site(self.src, node)))

    def _store_target(self, t: ast.expr) -> None:
        """Record the write behind one assignment target."""
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._store_target(e)
        elif isinstance(t, ast.Starred):
            self._store_target(t.value)
        elif isinstance(t, ast.Attribute):
            self._access(t, "write")
        elif isinstance(t, (ast.Subscript, ast.Slice)):
            self._access(t.value, "write")
        elif isinstance(t, ast.Name) and t.id in self._global_decls:
            self._access(t, "write")

    # -- structure ---------------------------------------------------------

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        prev = self.cls
        self.cls = node.name
        self.classes[node.name] = [
            b.id for b in node.bases if isinstance(b, ast.Name)
        ]
        self.generic_visit(node)
        self.cls = prev

    def _visit_def(self, node) -> None:
        prev_fn, prev_held = self.fn, self.held
        prev_exempt = self.exempt_acquires
        prev_globals = self._global_decls
        qual = (f"{self.module}.{self.cls}.{node.name}" if self.cls
                else f"{self.module}.{node.name}")
        if prev_fn is not None:        # nested def (closure): own summary,
            qual = f"{prev_fn.qualname}.<locals>.{node.name}"
            prev_fn.closures.append(qual)
        self.fn = FuncInfo(qual, self.module, self.cls, node.name,
                           node=node, src=self.src)
        self.held = []                 # a closure runs on its caller's
        self.funcs.append(self.fn)     # stack, modeled via bindings
        self.exempt_acquires = self._acquire_then_try(node)
        self._global_decls = {
            name for n in ast.walk(node)
            if isinstance(n, ast.Global) for name in n.names
        }
        for stmt in node.body:
            self.visit(stmt)
        self.fn, self.held = prev_fn, prev_held
        self.exempt_acquires = prev_exempt
        self._global_decls = prev_globals

    def _acquire_then_try(self, fn_node) -> set[int]:
        """Call-node ids of the conventional disciplined shape

            lock.acquire()
            try: ...
            finally: lock.release()

        — the acquire PRECEDES the try, so the finally-stack check in
        visit_Try cannot see it."""
        out: set[int] = set()
        for n in ast.walk(fn_node):
            for attr in ("body", "orelse", "finalbody"):
                stmts = getattr(n, attr, None)
                if not isinstance(stmts, list):
                    continue
                for a, b in zip(stmts, stmts[1:]):
                    if not (isinstance(a, ast.Expr)
                            and isinstance(a.value, ast.Call)
                            and isinstance(a.value.func, ast.Attribute)
                            and a.value.func.attr == "acquire"
                            and isinstance(b, ast.Try)):
                        continue
                    lid = self._lock_id(a.value.func.value)
                    if lid is None:
                        continue
                    for f in ast.walk(ast.Module(body=b.finalbody,
                                                 type_ignores=[])):
                        if (isinstance(f, ast.Call)
                                and isinstance(f.func, ast.Attribute)
                                and f.func.attr == "release"
                                and self._lock_id(f.func.value) == lid):
                            out.add(id(a.value))
        return out

    visit_FunctionDef = _visit_def
    visit_AsyncFunctionDef = _visit_def

    # -- lock events -------------------------------------------------------

    def _do_with(self, node) -> None:
        if self.fn is None:
            self.generic_visit(node)
            return
        pushed = 0
        for item in node.items:
            expr = item.context_expr
            lid = self._lock_id(expr)
            if lid is not None:
                w = site(self.src, expr)
                self.fn.acquires.setdefault(lid, w)
                for holder in self.held:
                    self.fn.edges.append((holder, lid, w))
                self.held.append(lid)
                pushed += 1
            elif self._is_sqlite_cm(expr):
                self._effect("sqlite", site(self.src, expr))
            else:
                self.visit(expr)
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(pushed):
            self.held.pop()

    visit_With = _do_with
    visit_AsyncWith = _do_with

    def visit_Try(self, node: ast.Try) -> None:
        released: set[str] = set()
        for stmt in ast.walk(ast.Module(body=node.finalbody,
                                        type_ignores=[])):
            if (isinstance(stmt, ast.Call)
                    and isinstance(stmt.func, ast.Attribute)
                    and stmt.func.attr == "release"):
                lid = self._lock_id(stmt.func.value)
                if lid is not None:
                    released.add(lid)
        self.finally_released.append(released)
        for stmt in node.body + node.handlers + node.orelse:
            self.visit(stmt)
        self.finally_released.pop()
        for stmt in node.finalbody:
            self.visit(stmt)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        # A lambda body runs on some later caller's stack, not here —
        # attributing its calls to the current held set would be wrong
        # in both directions; deliberate callbacks go through
        # hierarchy.CALLBACK_BINDINGS instead.
        return

    # -- shared-state accesses (lockset analyzer raw material) -------------

    def visit_Assign(self, node: ast.Assign) -> None:
        if self.fn is not None:
            for t in node.targets:
                self._store_target(t)
        if (self.cls is not None and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Attribute)
                and isinstance(node.targets[0].value, ast.Name)
                and node.targets[0].value.id == "self"
                and isinstance(node.value, ast.Call)):
            ctor = dotted(node.value.func)
            if ctor is not None:
                self.attr_ctors.setdefault(
                    f"{self.cls}.{node.targets[0].attr}", ctor)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        if self.fn is not None:
            self._store_target(node.target)
            # x += 1 reads x too, but the Store ctx hides it from
            # visit_Attribute — the write access carries the same held
            # set, so the lockset math is unaffected.
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if self.fn is not None and node.value is not None:
            self._store_target(node.target)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        if self.fn is not None:
            for t in node.targets:
                self._store_target(t)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if self.fn is not None and isinstance(node.ctx, ast.Load):
            self._access(node, "read")
        self.generic_visit(node)

    def _thread_target(self, node: ast.expr) -> list[str]:
        """Resolve a Thread(target=...) expression to entry identities
        ("Cls.meth" | "<module-basename>.fn"); [] when the target is an
        external bound method (e.g. httpd.serve_forever — unknown
        receiver, nothing in-tree to race-check). A DYNAMIC callable
        (lambda, functools.partial, a computed expression) resolves to
        the "<dynamic>" sentinel instead: it wraps in-tree code the
        role table can never see, so lockset flags the spawn rather
        than silently skipping it."""
        if isinstance(node, ast.IfExp):
            return self._thread_target(node.body) + \
                self._thread_target(node.orelse)
        if isinstance(node, ast.Attribute):
            base = node.value
            if isinstance(base, ast.Name) and base.id == "self" \
                    and self.cls is not None:
                return [f"{self.cls}.{node.attr}"]
            if isinstance(base, ast.Name) \
                    and base.id in hierarchy.ATTR_TYPES:
                t = hierarchy.ATTR_TYPES[base.id]
                return [f"{t}.{node.attr}"] if t else []
            return []
        if isinstance(node, ast.Name):
            return [f"{self.module.rsplit('.', 1)[-1]}.{node.id}"]
        if isinstance(node, (ast.Lambda, ast.Call)):
            return ["<dynamic>"]
        return []

    def _effect(self, kind: str, where: str) -> None:
        self.fn.effects.setdefault(kind, where)
        for holder in self.held:
            # Lexical effect-under-lock rides the edge list with a
            # pseudo-target so the checker sees it uniformly.
            self.fn.edges.append((holder, f"effect:{kind}", where))

    def visit_Call(self, node: ast.Call) -> None:
        if self.fn is None:
            self.generic_visit(node)
            return
        name = call_name(node)
        recv = receiver_name(node)
        where = site(self.src, node)
        if name is not None:
            # Container mutations through an attribute/global binding
            # are shared-state writes (lockset analyzer).
            if name in _MUTATORS and isinstance(node.func, ast.Attribute):
                self._access(node.func.value, "write")
            # Thread entry points: Thread(target=...) spawns must map to
            # a declared role (hierarchy.THREAD_ROLES).
            if name == "Thread":
                target = next(
                    (kw.value for kw in node.keywords
                     if kw.arg == "target"),
                    node.args[0] if node.args else None)
                if target is not None:
                    for ident in self._thread_target(target):
                        self.thread_targets.append((ident, where))
            # Bare .acquire() discipline (with-scoped locking only).
            if name == "acquire" and isinstance(node.func, ast.Attribute):
                lid = self._lock_id(node.func.value)
                if lid is not None \
                        and id(node) not in self.exempt_acquires \
                        and not any(
                            lid in s for s in self.finally_released):
                    self.fn.bare_acquires.append((where, lid))
            # Effects.
            d = dotted(node.func)
            if ((recv in _SQLITE_RECEIVERS and name in _SQLITE_METHODS)
                    or (d or "").startswith("sqlite3.")):
                self._effect("sqlite", where)
            elif ((recv == "pb2" and name[:1].isupper())
                  or (recv is None and name in self.proto_aliases)):
                self._effect("proto", where)
            else:
                self.fn.calls.append(
                    CallSite(name, recv, tuple(self.held), where))
        self.generic_visit(node)


# -- cross-module resolution -------------------------------------------------


class Graph:
    """The whole-program result: function summaries + the lock graph."""

    def __init__(self, sources: list[Source]):
        self.funcs: dict[str, FuncInfo] = {}
        self.by_method: dict[str, list[FuncInfo]] = {}
        self.by_class: dict[str, dict[str, FuncInfo]] = {}
        self.bases: dict[str, list[str]] = {}
        self.bare_acquire_sites: list[tuple[str, str]] = []
        self.mod_imports: dict[str, dict[str, str]] = {}
        self.attr_ctors: dict[str, str] = {}
        self.thread_targets: list[tuple[str, str]] = []
        self.proto_aliases: dict[str, set[str]] = {}
        for src in sources:
            a = _Analyzer(src)
            a.visit(src.tree)
            self.bases.update(a.classes)
            self.mod_imports[a.module] = a.imports
            self.attr_ctors.update(a.attr_ctors)
            self.thread_targets.extend(a.thread_targets)
            self.proto_aliases[a.module] = a.proto_aliases
            for f in a.funcs:
                self.funcs[f.qualname] = f
                self.by_method.setdefault(f.name, []).append(f)
                if f.cls:
                    self.by_class.setdefault(f.cls, {})[f.name] = f
                self.bare_acquire_sites.extend(f.bare_acquires)
        self._fixpoint()
        self.edges = self._collect_edges()

    def root_class(self, cls: str) -> str:
        """Topmost analyzed base: attribute state of a subclass IS its
        base's state (NativeLanesRunner inherits EngineRunner's)."""
        seen = set()
        while cls not in seen:
            seen.add(cls)
            b = self.bases.get(cls) or []
            if not b or b[0] not in self.bases:
                return cls
            cls = b[0]
        return cls

    # -- call resolution ---------------------------------------------------

    def _lookup(self, cls: str | None, name: str) -> FuncInfo | None:
        seen = set()
        while cls and cls not in seen:
            seen.add(cls)
            m = self.by_class.get(cls, {}).get(name)
            if m is not None:
                return m
            b = self.bases.get(cls) or []
            cls = b[0] if b else None
        return None

    def resolve(self, caller: FuncInfo, c: CallSite,
                skip_generic: bool = False) -> list[FuncInfo]:
        if c.name in hierarchy.CALLBACK_BINDINGS:
            out = []
            for target in hierarchy.CALLBACK_BINDINGS[c.name]:
                tcls, tname = target.rsplit(".", 1)
                m = self._lookup(tcls, tname)
                if m is not None:
                    out.append(m)
            return out
        if c.recv is None:
            # Bare name: module-local function, else an imported one
            # (`from pkg.mod import f` -> pkg.mod.f; a package import
            # resolves into its __init__ module).
            m = self.funcs.get(f"{caller.module}.{c.name}")
            if m is None:
                bound = self.mod_imports.get(caller.module, {}).get(c.name)
                if bound:
                    mod, name = bound
                    m = (self.funcs.get(f"{mod}.{name}")
                         or self.funcs.get(f"{mod}.__init__.{name}"))
            return [m] if m is not None else []
        if c.recv == "self":
            m = self._lookup(caller.cls, c.name)
            return [m] if m is not None else []
        if c.recv in hierarchy.ATTR_TYPES:
            t = hierarchy.ATTR_TYPES[c.recv]
            if t is None or t == "sqlite3":
                return []
            m = self._lookup(t, c.name)
            return [m] if m is not None else []
        # Unknown receiver: conservative name-based fan-out. Callers
        # that PROPAGATE context through the graph (lockset roles, the
        # determinism closure) pass skip_generic=True to drop container/
        # queue method names, where the receiver is almost always a
        # plain list/dict/queue and the fan-out would smear every
        # analyzed class sharing the name (e.g. a local `events.append`
        # resolving into RetransmissionRing.append). The lock-order
        # effect fixpoint keeps the full fan-out — over-approximating
        # effects is safe, losing a `close`-commits-SQLite edge is not.
        if skip_generic and c.name in _GENERIC_METHODS:
            return []
        return self.by_method.get(c.name, [])

    # -- fixpoint ----------------------------------------------------------

    def _fixpoint(self) -> None:
        for f in self.funcs.values():
            f.trans_acquires = dict(f.acquires)
            f.trans_effects = dict(f.effects)
        changed = True
        while changed:
            changed = False
            for f in self.funcs.values():
                for c in f.calls:
                    for callee in self.resolve(f, c):
                        for lid, w in callee.trans_acquires.items():
                            if lid not in f.trans_acquires:
                                f.trans_acquires[lid] = \
                                    f"{c.where} -> {w}"
                                changed = True
                        for eff, w in callee.trans_effects.items():
                            if eff not in f.trans_effects:
                                f.trans_effects[eff] = \
                                    f"{c.where} -> {w}"
                                changed = True

    def _collect_edges(self) -> dict[tuple[str, str], str]:
        """(holder_id, target) -> first witness. target is a lock id or
        'effect:<kind>' pseudo-node, or 'leaf:<qualname>' annotations
        are folded into the witness text."""
        edges: dict[tuple[str, str], str] = {}
        for f in sorted(self.funcs.values(), key=lambda x: x.qualname):
            for holder, target, w in f.edges:
                edges.setdefault((holder, target), w)
            for c in f.calls:
                if not c.held:
                    continue
                for callee in self.resolve(f, c):
                    for lid, w in callee.trans_acquires.items():
                        for holder in c.held:
                            edges.setdefault(
                                (holder, lid),
                                f"{c.where} -> {callee.qualname} ({w})")
                    for eff, w in callee.trans_effects.items():
                        for holder in c.held:
                            edges.setdefault(
                                (holder, f"effect:{eff}"),
                                f"{c.where} -> {callee.qualname} ({w})")
        return edges


def level_of(lock_id: str) -> str:
    """Declared level name, or the raw identity for untracked locks."""
    return _ID_TO_LEVEL.get(lock_id, lock_id)


def _leaf_function(witness: str) -> str:
    """The last resolved function in a witness chain, for waiver
    matching (waivers name what is REACHED, not the path)."""
    leaf = ""
    for tok in witness.replace("(", " ").replace(")", " ").split():
        if tok and not tok[0].isdigit() and "/" not in tok and tok != "->":
            leaf = tok
    return leaf.rsplit(".", 1)[-1] if leaf else ""


def check(graph: Graph) -> list[Violation]:
    vs: list[Violation] = []

    # 1/2/3: ordering over the extracted edge set.
    level_edges: dict[tuple[str, str], str] = {}
    for (holder, target), w in sorted(graph.edges.items()):
        if target.startswith("effect:"):
            continue
        ha, ta = level_of(holder), level_of(target)
        if (ha, ta) not in level_edges:
            level_edges[(ha, ta)] = w
    for (ha, ta), w in sorted(level_edges.items()):
        if ha == ta:
            vs.append(Violation(
                "lock-order/self-deadlock", w,
                f"'{ha}' re-acquired while already held "
                f"(threading.Lock is not reentrant)"))
        elif ha in _DECLARED and ta in _DECLARED:
            if (ta, ha) in _CLOSURE:
                vs.append(Violation(
                    "lock-order/inversion", w,
                    f"'{ta}' must be acquired before '{ha}' per the "
                    f"declared hierarchy, but '{ha}' is held here"))
            elif (ha, ta) not in _CLOSURE:
                vs.append(Violation(
                    "lock-order/undeclared-edge", w,
                    f"'{ha}' -> '{ta}' nesting is not declared in "
                    f"analysis/hierarchy.py ORDER — declare it "
                    f"deliberately or restructure"))

    # Cycles among untracked locks (tracked ones are covered above).
    adj: dict[str, set[str]] = {}
    for (ha, ta) in level_edges:
        if ha != ta:
            adj.setdefault(ha, set()).add(ta)
    state: dict[str, int] = {}

    def dfs(n: str, path: list[str]) -> None:
        state[n] = 1
        for m in sorted(adj.get(n, ())):
            if state.get(m, 0) == 1:
                cyc = path[path.index(m):] + [m] if m in path else [n, m]
                if not all(x in _DECLARED for x in cyc):
                    vs.append(Violation(
                        "lock-order/cycle", " -> ".join(cyc + [cyc[0]]),
                        "cyclic lock acquisition (deadlock window)"))
            elif state.get(m, 0) == 0:
                dfs(m, path + [m])
        state[n] = 2

    for n in sorted(adj):
        if state.get(n, 0) == 0:
            dfs(n, [n])

    # 4: forbidden effects under declared locks.
    for (holder, target), w in sorted(graph.edges.items()):
        if not target.startswith("effect:"):
            continue
        eff = target.split(":", 1)[1]
        lvl = level_of(holder)
        if eff in hierarchy.FORBIDDEN_UNDER.get(lvl, ()):
            leaf = _leaf_function(w)
            if ("lock-order/forbidden-effect", lvl, leaf) \
                    in hierarchy.WAIVERS:
                continue
            what = ("SQLite call" if eff == "sqlite"
                    else "proto materialization")
            vs.append(Violation(
                "lock-order/forbidden-effect", w,
                f"{what} reachable while holding '{lvl}'"))

    # 5: bare .acquire() discipline. (try/finally-scoped acquires are
    # rewritten as `with` in this codebase; any .acquire() is a defect.)
    for where, lid in sorted(graph.bare_acquire_sites):
        vs.append(Violation(
            "lock-order/bare-acquire", where,
            f"bare {lid}.acquire() — use a `with` block (or a "
            f"try/finally that provably releases)"))

    return vs


def build_graph() -> Graph:
    return Graph(load_sources(SCAN_DIRS))


def run() -> list[Violation]:
    return check(build_graph())
