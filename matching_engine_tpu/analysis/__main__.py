"""CLI for the static-analysis suite (scripts/check.sh drives this).

    python -m matching_engine_tpu.analysis run [--json FILE]
    python -m matching_engine_tpu.analysis render-concurrency [--check]

`run` exits nonzero on any violation; `--json` also writes a summary
artifact (per-analyzer counts + every violation row). `render-concurrency
--check` exits 3 when docs/CONCURRENCY.md is stale instead of writing.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="matching_engine_tpu.analysis")
    sub = p.add_subparsers(dest="cmd", required=True)
    runp = sub.add_parser("run", help="run all analyzers, exit 1 on "
                                      "violations")
    runp.add_argument("--json", default=None, metavar="FILE",
                      help="write a machine-readable summary artifact")
    renp = sub.add_parser("render-concurrency",
                          help="regenerate docs/CONCURRENCY.md")
    renp.add_argument("--check", action="store_true",
                      help="exit 3 if the committed doc is stale "
                           "(write nothing)")
    args = p.parse_args(argv)

    if args.cmd == "render-concurrency":
        from matching_engine_tpu.analysis import render
        from matching_engine_tpu.analysis.common import REPO_ROOT

        path = REPO_ROOT / "docs" / "CONCURRENCY.md"
        fresh = render.render()
        if args.check:
            if not path.exists() or path.read_text() != fresh:
                print("docs/CONCURRENCY.md is stale — regenerate with "
                      "`python -m matching_engine_tpu.analysis "
                      "render-concurrency`", file=sys.stderr)
                return 3
            print("docs/CONCURRENCY.md is fresh")
            return 0
        print(render.write())
        return 0

    from matching_engine_tpu.analysis import run_all

    results = run_all()
    total = 0
    for name, vs in results.items():
        status = "clean" if not vs else f"{len(vs)} violation(s)"
        print(f"[{name}] {status}")
        for v in vs:
            print(f"  {v}")
        total += len(vs)
    if args.json:
        with open(args.json, "w") as f:
            json.dump({
                "total_violations": total,
                "analyzers": {
                    name: [dataclasses.asdict(v) for v in vs]
                    for name, vs in results.items()
                },
            }, f, indent=2, sort_keys=True)
        print(f"summary: {args.json}")
    return 1 if total else 0


if __name__ == "__main__":
    sys.exit(main())
