"""Determinism-taint analyzer: machine-enforce the bit-identical-replay
contract the HA design (ROADMAP item 3, arXiv:2402.09527) rests on.

The replay surfaces — storage row construction, feed / drop-copy
payloads, seq stamping, checkpoint contents — must be pure functions of
the sequenced op log. Today that is review prose plus parity tests that
only cover the schedules the tests happen to run. This analyzer walks
the replay closure statically:

1. SINKS are discovered structurally: any function that appends to the
   storage/stream row lists, constructs a wire row (FillRow,
   pb2.OrderUpdate/MarketDataUpdate and their aliases), stamps
   `.seq`/`.feed_epoch`/`.next_seq`, writes SQL in storage/, or writes
   checkpoint blocks. The replay closure is those functions plus
   everything they transitively call (lockorder's conservative call
   resolution: receiver typing, imports, closures).
2. determinism/forbidden-source: random / np.random / uuid / secrets /
   os.urandom / thread identifiers anywhere in the replay closure.
   These have no legitimate use on a replay path, so plain reachability
   suffices — no dataflow needed.
3. determinism/wallclock-taint: a real (interprocedural, fixpoint)
   taint pass from wall-clock reads (`time.*`, `datetime.*`) and
   `id()` to the sink expressions. Taint flows through local
   assignments, attribute stores (`self.epoch = time.time()…` taints
   every later `sequencer.epoch` read), function returns, and call
   arguments into scanned callees. Observability stamps that feed
   metrics/timelines never reach a sink expression and therefore never
   fire — the matcher is the sink, not the source.
4. determinism/unordered-iteration: set-typed or dict-view iteration
   (not wrapped in sorted()) that feeds a sink expression — hash-order
   (PYTHONHASHSEED) and thread-insertion-order dependence on a replay
   surface.

Fields *declared* wall-clock — ingress timestamps in the drop-copy
envelope, the per-boot feed epoch, the store's audit `ts` columns — are
allowlisted in hierarchy.DETERMINISM_WAIVERS with a witness each, so
the replica's bit-identity contract is explicit about exactly which
bytes are exempt (and parity comparisons normalize exactly those).
"""

from __future__ import annotations

import ast

from matching_engine_tpu.analysis import hierarchy
from matching_engine_tpu.analysis.common import (
    Violation,
    call_name,
    dotted,
    load_sources,
    site,
)
from matching_engine_tpu.analysis.lockorder import CallSite, Graph

# The replay-bearing packages: both serving paths' decode/publish
# layers, the feed, the audit stream, durable storage, the record
# codecs, the engine harness, checkpointing, and the scenario-workload
# recorder (sim/record.py — a recorded opfile is a replay artifact whose
# bytes must be a pure function of (config, scenario, seed)), and the
# many-venue gym (gym/ — a frozen episode is the same artifact class).
REPLAY_SCAN_DIRS = ("server", "feed", "audit", "storage", "domain",
                    "engine", "replication", "sim", "gym",
                    "utils/checkpoint.py")

# Rule 2 — sources with no legitimate replay-path use (reachability).
_FORBIDDEN_HEADS = ("random.", "np.random.", "numpy.random.", "uuid.",
                    "secrets.")
_FORBIDDEN_CALLS = frozenset({
    "os.urandom", "threading.get_ident", "threading.current_thread",
    "get_ident", "current_thread",
})

# Rule 3 — wall-clock family (taint-tracked, waivable per declared
# field) plus id(): address-derived values change every run.
_WALLCLOCK_HEADS = ("time.", "datetime.")
_TAINT_BARE = frozenset({"id"})

_OUTPUT_LISTS = frozenset({
    "storage_orders", "storage_updates", "storage_fills",
    "order_updates", "market_data",
})
_ROW_CTORS = frozenset({"FillRow", "OrderUpdate", "MarketDataUpdate"})
_STAMP_ATTRS = frozenset({"seq", "feed_epoch", "next_seq"})
_CKPT_WRITERS = frozenset({"savez", "savez_compressed", "dump",
                           "_atomic_checkpoint_write"})
_SQL_WRITERS = frozenset({"execute", "executemany", "executescript"})
# Recorded workload artifacts (domain/oprec.write_opfile): every byte of
# an opfile is replay payload — the sim recorder's determinism contract.
_OPFILE_WRITERS = frozenset({"write_opfile"})


def _shallow_walk(node):
    """Pre-order, document-order walk that does not descend into nested
    defs/lambdas (their bodies belong to their own FuncInfo). Document
    order matters: the taint pass relies on def-before-use converging
    within its two statement sweeps."""
    stack = list(ast.iter_child_nodes(node))[::-1]
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        stack.extend(list(ast.iter_child_nodes(n))[::-1])


def _forbidden_call(node: ast.Call) -> str | None:
    d = dotted(node.func)
    if d is None:
        return None
    if d in _FORBIDDEN_CALLS:
        return d
    for head in _FORBIDDEN_HEADS:
        if d.startswith(head):
            return d
    return None


def _wallclock_call(node: ast.Call) -> str | None:
    """Taint origin for a source call: the wall-clock family and id(),
    PLUS the forbidden-source family. Rule 1 catches forbidden sources
    inside the sink→callee closure by reachability; seeding the taint
    pass with them too closes the caller direction — RNG computed in a
    caller and passed as an argument into a sink function still reaches
    the sink as `<origin>-derived`."""
    d = dotted(node.func)
    if d is None:
        return None
    if d in _TAINT_BARE or d in _FORBIDDEN_CALLS:
        return d
    for head in _WALLCLOCK_HEADS + _FORBIDDEN_HEADS:
        if d.startswith(head):
            return d
    return None


class _Sinks:
    """Structural sink matchers for one function, module-aware (proto
    aliases, storage-only SQL)."""

    def __init__(self, graph: Graph, f):
        self.graph = graph
        self.f = f
        self.aliases = graph.proto_aliases.get(f.module, set())
        self.in_storage = ".storage." in f.module \
            or f.module.endswith(".storage")

    def output_call(self, node: ast.Call) -> str | None:
        """A call whose ARGUMENTS are replay payload, or None."""
        name = call_name(node)
        if name in ("append", "extend") \
                and isinstance(node.func, ast.Attribute):
            base = node.func.value
            attr = base.attr if isinstance(base, ast.Attribute) else (
                base.id if isinstance(base, ast.Name) else None)
            if attr in _OUTPUT_LISTS:
                return f"{attr}.{name}"
        if name in _ROW_CTORS or name in self.aliases:
            return f"{name}()"
        d = dotted(node.func) or ""
        if name in _CKPT_WRITERS and (
                d.startswith("np.") or d.startswith("json.")
                or name == "_atomic_checkpoint_write"):
            if "checkpoint" in self.f.module:
                return f"{name}()"
        if self.in_storage and name in _SQL_WRITERS:
            return f"{name}()"
        if name in _OPFILE_WRITERS:
            return f"{name}()"
        return None

    def output_assign(self, node) -> str | None:
        """A store whose TARGET is replay payload (seq stamping)."""
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for t in targets:
            if isinstance(t, ast.Attribute) and t.attr in _STAMP_ATTRS:
                return f".{t.attr} stamp"
        return None


def _params(node) -> list[str]:
    a = node.args
    names = [p.arg for p in a.posonlyargs + a.args]
    return names


def _find_sinks(graph: Graph):
    """qual -> list of (kind, node) output expressions."""
    out: dict[str, list] = {}
    for qual, f in graph.funcs.items():
        if f.node is None:
            continue
        sinks = _Sinks(graph, f)
        rows = []
        for n in _shallow_walk(f.node):
            if isinstance(n, ast.Call):
                label = sinks.output_call(n)
                if label:
                    rows.append((label, n))
            elif isinstance(n, (ast.Assign, ast.AugAssign)):
                label = sinks.output_assign(n)
                if label:
                    rows.append((label, n))
        if rows:
            out[qual] = rows
    return out


def _replay_closure(graph: Graph, seeds) -> dict[str, str]:
    """qual -> sink root that pulled it in (BFS over resolvable calls
    and closures)."""
    reach: dict[str, str] = {q: q for q in seeds}
    stack = list(seeds)
    while stack:
        qual = stack.pop()
        f = graph.funcs[qual]
        nxt = [c.qualname for call in f.calls
               for c in graph.resolve(f, call, skip_generic=True)]
        nxt += f.closures
        for cq in nxt:
            if cq in graph.funcs and cq not in reach:
                reach[cq] = reach[qual]
                stack.append(cq)
    return reach


# -- the taint pass ----------------------------------------------------------


class _TaintState:
    def __init__(self):
        self.params: dict[str, dict[str, str]] = {}   # qual -> {param: origin}
        self.attrs: dict[str, str] = {}               # Class.attr -> origin
        self.returns: dict[str, str] = {}             # qual -> origin
        self.changed = False

    def taint_param(self, qual: str, param: str, origin: str) -> None:
        d = self.params.setdefault(qual, {})
        if param not in d:
            d[param] = origin
            self.changed = True

    def taint_attr(self, key: str, origin: str) -> None:
        if key not in self.attrs:
            self.attrs[key] = origin
            self.changed = True

    def taint_return(self, qual: str, origin: str) -> None:
        if qual not in self.returns:
            self.returns[qual] = origin
            self.changed = True


class _FuncTaint:
    """One function's forward taint pass (run to a local fixpoint each
    global iteration; propagates into callees via the shared state)."""

    def __init__(self, graph: Graph, f, state: _TaintState):
        self.graph = graph
        self.f = f
        self.state = state
        self.local: dict[str, str] = dict(
            state.params.get(f.qualname, {}))

    def _attr_key(self, node: ast.Attribute) -> str | None:
        base = node.value
        if isinstance(base, ast.Name) and base.id == "self" and self.f.cls:
            return f"{self.f.cls}.{node.attr}"
        if isinstance(base, ast.Name) \
                and base.id in hierarchy.ATTR_TYPES:
            t = hierarchy.ATTR_TYPES[base.id]
            if t and t != "sqlite3":
                return f"{t}.{node.attr}"
        return None

    def expr_origin(self, node) -> str | None:
        """Origin token if the expression carries taint. Also runs the
        call-argument propagation side effect. Constructor calls of
        scanned classes are a taint BARRIER at the reference level: the
        new object is clean, but tainted arguments flow into its
        __init__ params (and from there into attribute taint) — without
        the barrier, one wall-clock ctor argument (e.g. the spill dir's
        epoch path) would mark the object and everything later read off
        it, drowning the true field-level flows."""
        if node is None or not isinstance(node, ast.AST):
            return None
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name in self.graph.bases:
                init = self.graph.by_class.get(name, {}).get("__init__")
                self._propagate_args(node, [init] if init else [])
                return None
            w = _wallclock_call(node)
            resolved, origin = self._call_origin(node)
            if not resolved:
                # Unresolved callee (builtin/external): conservatively,
                # the result of f(tainted) — or of a method on a tainted
                # object — is tainted. Resolved callees are trusted: the
                # returns summary already reflects their body.
                for arg in node.args:
                    a = arg.value if isinstance(arg, ast.Starred) else arg
                    origin = origin or self.expr_origin(a)
                for kw in node.keywords:
                    origin = origin or self.expr_origin(kw.value)
                if isinstance(node.func, ast.Attribute):
                    origin = origin or self.expr_origin(node.func.value)
            return w or origin
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, ast.Load):
                return _collapse(self.local.get(node.id))
            return None
        if isinstance(node, ast.Attribute):
            if not isinstance(node.ctx, ast.Load):
                return None
            key = self._attr_key(node)
            if key is not None and key in self.state.attrs:
                return self.state.attrs[key]
            return self.expr_origin(node.value)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return None
        origin = None
        for child in ast.iter_child_nodes(node):
            origin = origin or self.expr_origin(child)
        return origin

    def _call_origin(self, node: ast.Call) -> tuple[bool, str | None]:
        """Propagate tainted arguments into resolvable callees; return
        (resolved-to-a-scanned-body, return-taint origin)."""
        name = call_name(node)
        if name is None:
            return False, None
        cs = CallSite(name, _recv(node), (), "")
        callees = [c for c in self.graph.resolve(self.f, cs,
                                                 skip_generic=True)
                   if c is not None and c.node is not None]
        if not callees:
            return False, None
        return True, self._propagate_args(node, callees)

    def _propagate_args(self, node: ast.Call, callees) -> str | None:
        origin = None
        for callee in callees:
            if callee is None or callee.node is None:
                continue
            params = _params(callee.node)
            if params and params[0] == "self":
                params = params[1:]
            pos = 0
            for arg in node.args:
                if isinstance(arg, ast.Starred):
                    s = self.expr_struct(arg.value)
                    if isinstance(s, list):
                        # *env with a known tuple shape: element-wise.
                        for j, el in enumerate(s):
                            o = _collapse(el)
                            if o and pos + j < len(params):
                                self.state.taint_param(
                                    callee.qualname, params[pos + j], o)
                        pos += len(s)
                    else:
                        o = _collapse(s)
                        if o:
                            for p in params[pos:]:
                                self.state.taint_param(
                                    callee.qualname, p, o)
                        pos = len(params)
                    continue
                s = self.expr_struct(arg)
                if _collapse(s) is not None and pos < len(params):
                    self.state.taint_param(callee.qualname, params[pos], s)
                pos += 1
            for kw in node.keywords:
                o = self.expr_struct(kw.value)
                if _collapse(o) is not None and kw.arg is not None:
                    self.state.taint_param(callee.qualname, kw.arg, o)
            ret = self.state.returns.get(callee.qualname)
            origin = origin or ret
        return origin

    def expr_struct(self, node):
        """Structured origin: a literal tuple/list keeps PER-ELEMENT
        origins, so `rows, md, env, flag = item` taints only the
        elements that actually carry wall clock — without this, one
        ingress stamp in a dispatch envelope tuple would mark every row
        list travelling beside it."""
        if isinstance(node, (ast.Tuple, ast.List)) \
                and isinstance(getattr(node, "ctx", ast.Load()), ast.Load):
            return [self.expr_struct(e) for e in node.elts]
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            return self.local.get(node.id)
        return self.expr_origin(node)

    def run(self) -> None:
        for _ in range(2):   # two passes: later stmts can taint earlier uses
            for n in _shallow_walk(self.f.node):
                if isinstance(n, ast.Assign):
                    o = self.expr_struct(n.value)
                    if _collapse(o) is None:
                        continue
                    for t in n.targets:
                        self._taint_target(t, o)
                elif isinstance(n, ast.AugAssign):
                    o = self.expr_origin(n.value)
                    if o is not None:
                        self._taint_target(n.target, o)
                elif isinstance(n, ast.For):
                    o = self.expr_origin(n.iter)
                    if o is not None:
                        self._taint_target(n.target, o)
                elif isinstance(n, ast.Return) and n.value is not None:
                    o = self.expr_origin(n.value)
                    if o is not None:
                        self.state.taint_return(self.f.qualname, o)
                elif isinstance(n, ast.Call):
                    self._call_origin(n)   # plain-statement propagation

    def _taint_target(self, t: ast.expr, origin) -> None:
        if isinstance(t, (ast.Tuple, ast.List)):
            if isinstance(origin, list) and len(origin) == len(t.elts):
                for e, o in zip(t.elts, origin):   # element-wise unpack
                    if _collapse(o) is not None:
                        self._taint_target(e, o)
            else:
                o = _collapse(origin)
                for e in t.elts:
                    self._taint_target(e, o)
        elif isinstance(t, ast.Starred):
            self._taint_target(t.value, _collapse(origin))
        elif isinstance(t, ast.Name):
            if t.id not in self.local:
                self.local[t.id] = origin
        elif isinstance(t, ast.Subscript):
            # env["k"] = tainted: the container now carries the taint.
            self._taint_target(t.value, _collapse(origin))
        elif isinstance(t, ast.Attribute):
            key = self._attr_key(t)
            if key is not None:
                self.state.taint_attr(key, _collapse(origin))


def _recv(node: ast.Call) -> str | None:
    from matching_engine_tpu.analysis.common import receiver_name

    return receiver_name(node)


def _collapse(o) -> str | None:
    """Flatten a structured origin (str | list-of-origins | None) to the
    first concrete source token, or None."""
    if o is None or isinstance(o, str):
        return o
    for e in o:
        c = _collapse(e)
        if c is not None:
            return c
    return None


# -- unordered iteration -----------------------------------------------------


class _OrderCheck:
    """Set-typed / dict-view iteration feeding a sink expression."""

    def __init__(self, graph: Graph, f):
        self.graph = graph
        self.f = f
        # local name -> True when bound to an unordered collection
        self.unordered_names: set[str] = set()
        for n in _shallow_walk(f.node):
            if isinstance(n, ast.Assign) and self._unordered_expr(n.value):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        self.unordered_names.add(t.id)

    def _unordered_expr(self, node: ast.expr) -> bool:
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name in ("set", "frozenset"):
                return True
            if name in ("list", "tuple", "sorted", "reversed",
                        "enumerate"):
                if name == "sorted":
                    return False
                return bool(node.args) and \
                    self._unordered_expr(node.args[0])
            if name in ("keys", "values", "items") \
                    and isinstance(node.func, ast.Attribute):
                return True
        if isinstance(node, ast.Name):
            return node.id in self.unordered_names
        if isinstance(node, ast.Attribute):
            base = node.value
            owner = None
            if isinstance(base, ast.Name) and base.id == "self":
                owner = self.f.cls
            if owner is not None:
                ctor = self.graph.attr_ctors.get(f"{owner}.{node.attr}")
                if ctor in ("set", "frozenset"):
                    return True
        return False

    def check(self, sinks) -> list[tuple[str, ast.AST]]:
        """(iteration description, sink node) pairs where an unordered
        iteration encloses or feeds a sink expression."""
        hits: list[tuple[str, ast.AST]] = []
        sink_nodes = {id(n) for _, n in sinks}

        def walk(node, loop_unordered: list):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                entered = False
                if isinstance(child, ast.For) \
                        and self._unordered_expr(child.iter):
                    loop_unordered.append(child)
                    entered = True
                if id(child) in sink_nodes:
                    if loop_unordered:
                        hits.append(("inside unordered loop", child))
                    # a comprehension over an unordered iterable INSIDE
                    # the sink expression
                    for sub in _shallow_walk(child):
                        if isinstance(sub, (ast.ListComp, ast.GeneratorExp,
                                            ast.SetComp)):
                            for gen in sub.generators:
                                if self._unordered_expr(gen.iter):
                                    hits.append(
                                        ("comprehension over unordered "
                                         "iterable", child))
                walk(child, loop_unordered)
                if entered:
                    loop_unordered.pop()

        walk(self.f.node, [])
        return hits


# -- the checker -------------------------------------------------------------


def _short(qual: str) -> str:
    """module.Class.meth -> Class.meth | pkg.mod.fn -> mod.fn."""
    parts = qual.split(".")
    return ".".join(parts[-2:])


def check(graph: Graph) -> list[Violation]:
    vs: list[Violation] = []
    sinks = _find_sinks(graph)
    closure = _replay_closure(graph, sorted(sinks))

    # Rule 1: forbidden sources by reachability.
    for qual in sorted(closure):
        f = graph.funcs[qual]
        if f.node is None:
            continue
        for n in _shallow_walk(f.node):
            if isinstance(n, ast.Call):
                bad = _forbidden_call(n)
                if bad is not None and not _waived(
                        "determinism/forbidden-source", qual, bad):
                    vs.append(Violation(
                        "determinism/forbidden-source",
                        site(f.src, n),
                        f"{bad}() in {_short(qual)}, reachable from "
                        f"replay sink {_short(closure[qual])} — a replay "
                        f"surface may never read nondeterminism"))

    # Rule 2: wall-clock/id taint into sink expressions (fixpoint).
    state = _TaintState()
    for _ in range(32):
        state.changed = False
        for qual in sorted(graph.funcs):
            f = graph.funcs[qual]
            if f.node is not None:
                _FuncTaint(graph, f, state).run()
        if not state.changed:
            break
    for qual in sorted(sinks):
        f = graph.funcs[qual]
        ft = _FuncTaint(graph, f, state)
        ft.run()    # rebuild local taint for the final read
        for label, node in sinks[qual]:
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                origin = ft.expr_origin(
                    node.value if node.value is not None else node)
            else:
                origin = None
                for arg in list(node.args) + [kw.value
                                              for kw in node.keywords]:
                    a = arg.value if isinstance(arg, ast.Starred) else arg
                    origin = origin or ft.expr_origin(a)
            if origin is not None and not _waived(
                    "determinism/wallclock-taint", qual, origin):
                vs.append(Violation(
                    "determinism/wallclock-taint", site(f.src, node),
                    f"{origin}-derived value reaches replay output "
                    f"{label} in {_short(qual)} — declare the field "
                    f"wall-clock in hierarchy.DETERMINISM_WAIVERS or "
                    f"derive it from the op log"))

    # Rule 3: unordered iteration feeding sink expressions.
    for qual in sorted(sinks):
        f = graph.funcs[qual]
        oc = _OrderCheck(graph, f)
        for why, node in oc.check(sinks[qual]):
            if not _waived("determinism/unordered-iteration", qual, why):
                vs.append(Violation(
                    "determinism/unordered-iteration", site(f.src, node),
                    f"replay output in {_short(qual)} built {why} — "
                    f"set/dict iteration order is not replay-stable; "
                    f"sort it"))
    return list(dict.fromkeys(vs))


def _waived(rule: str, qual: str, token: str) -> bool:
    short = _short(qual)
    for r, fn, tok in hierarchy.DETERMINISM_WAIVERS:
        if r == rule and fn == short and (tok == "*" or tok == token
                                          or token.startswith(tok)):
            return True
    return False


def build_graph() -> Graph:
    return Graph(load_sources(REPLAY_SCAN_DIRS))


def run() -> list[Violation]:
    return check(build_graph())
