"""ABI cross-checker: statically prove the python↔C++ record layouts
agree without building the .so.

Three byte-contracts are load-bearing and were each the site of review
churn when they drifted:

- `MeOpRec` (native/me_gwop.h) == `OPREC_DTYPE` (domain/oprec.py): the
  batch-edge wire format — one skewed offset silently corrupts every
  SubmitOrderBatch payload the C++ converter decodes;
- `MeGwOp` (native/me_gwop.h) == the ctypes mirror in
  native/__init__.py: the gateway ring record both edges push;
- `MeOp` (native/me_native.cpp) == its ctypes mirror: the lane ring op.

The checker parses the C struct declarations with a small tokenizer,
computes offsets under natural (System V x86-64 / AArch64) alignment —
the rule both `static_assert(sizeof...)` pins assume — and compares
field-by-field against the imported numpy dtype / ctypes Structures
(imports are layout-only; nothing loads or builds native code). It also
enforces explicit little-endian `struct` format strings package-wide:
a bare "@"-aligned format would re-introduce platform-dependent
padding at the exact seams this checker guards.
"""

from __future__ import annotations

import ast
import ctypes
import re
import sys

from matching_engine_tpu.analysis.common import (
    PKG_ROOT,
    REPO_ROOT,
    Violation,
    dotted,
    load_sources,
    site,
)

# C scalar type -> (size, numpy-ish kind). Alignment == size for
# scalars on every ABI this engine targets (x86-64, AArch64 TPU hosts).
_C_TYPES = {
    "uint8_t": (1, "u"), "int8_t": (1, "i"),
    "uint16_t": (2, "u"), "int16_t": (2, "i"),
    "uint32_t": (4, "u"), "int32_t": (4, "i"),
    "uint64_t": (8, "u"), "int64_t": (8, "i"),
    "char": (1, "S"),
    "float": (4, "f"), "double": (8, "f"),
}

_FIELD_RE = re.compile(
    r"^\s*(?P<type>\w+)\s+(?P<name>\w+)\s*(?:\[\s*(?P<n>\d+)\s*\])?\s*;")


def parse_struct(text: str, name: str) -> list[tuple[str, str, int]]:
    """Extract (type, field, array_n) rows for `struct <name>` from C++
    source text. Comments are stripped; only simple scalar/char-array
    members are supported — which is the point: these wire structs must
    STAY simple enough to mirror."""
    m = re.search(rf"struct\s+{name}\s*\{{(.*?)\}}\s*;", text, re.S)
    if m is None:
        raise ValueError(f"struct {name} not found")
    body = re.sub(r"//.*?$", "", m.group(1), flags=re.M)
    body = re.sub(r"/\*.*?\*/", "", body, flags=re.S)
    fields = []
    for line in body.splitlines():
        fm = _FIELD_RE.match(line)
        if fm:
            fields.append((fm.group("type"), fm.group("name"),
                           int(fm.group("n") or 1)))
    if not fields:
        raise ValueError(f"struct {name}: no parseable members")
    return fields


def c_layout(fields) -> tuple[dict[str, tuple[int, int, str]], int]:
    """Natural-alignment offsets: field -> (offset, size, kind), plus
    sizeof (end padded to max member alignment)."""
    out: dict[str, tuple[int, int, str]] = {}
    off = 0
    max_align = 1
    for ctype, name, n in fields:
        if ctype not in _C_TYPES:
            raise ValueError(f"{name}: unsupported C type {ctype}")
        size, kind = _C_TYPES[ctype]
        align = size            # scalar alignment; arrays align as elem
        off = (off + align - 1) // align * align
        out[name] = (off, size * n, kind)
        off += size * n
        max_align = max(max_align, align)
    return out, (off + max_align - 1) // max_align * max_align


def _norm(name: str) -> str:
    return name.lstrip("_")


def compare_layouts(cname: str, cfields: dict[str, tuple[int, int, str]],
                    csize: int, pname: str,
                    pfields: dict[str, tuple[int, int, str]],
                    psize: int) -> list[Violation]:
    """Field-by-field agreement between a C layout and a python-side
    layout (numpy dtype or ctypes). Names match modulo leading
    underscores; char boxes accept numpy S (bytes) or V (opaque pad)."""
    vs: list[Violation] = []
    where = f"{cname} vs {pname}"
    cn = {_norm(k): v for k, v in cfields.items()}
    pn = {_norm(k): v for k, v in pfields.items()}
    for f in cn:
        if f not in pn:
            vs.append(Violation(
                "abi/missing-field", where,
                f"C field '{f}' has no python-side mirror"))
    for f in pn:
        if f not in cn:
            vs.append(Violation(
                "abi/missing-field", where,
                f"python field '{f}' has no C-side member"))
    for f, (coff, csz, ckind) in sorted(cn.items()):
        if f not in pn:
            continue
        poff, psz, pkind = pn[f]
        if coff != poff:
            vs.append(Violation(
                "abi/offset-mismatch", where,
                f"'{f}': C offset {coff} != python offset {poff}"))
        if csz != psz:
            vs.append(Violation(
                "abi/width-mismatch", where,
                f"'{f}': C width {csz} != python width {psz}"))
        kinds_ok = (ckind == pkind
                    or (ckind == "S" and pkind in ("S", "V"))
                    or (pkind == "V" and csz == psz))
        if not kinds_ok:
            vs.append(Violation(
                "abi/kind-mismatch", where,
                f"'{f}': C kind '{ckind}' != python kind '{pkind}'"))
    if csize != psize:
        vs.append(Violation(
            "abi/total-size", where,
            f"sizeof mismatch: C {csize} != python {psize} (alignment "
            f"padding drifted)"))
    return vs


def dtype_layout(dtype) -> tuple[dict[str, tuple[int, int, str]], int,
                                 list[Violation]]:
    """numpy structured dtype -> (fields, itemsize, endianness
    violations). Multi-byte numerics must be EXPLICITLY
    little-endian — '=' would flip on a big-endian host while the C++
    side stays LE."""
    vs: list[Violation] = []
    out: dict[str, tuple[int, int, str]] = {}
    for name in dtype.names:
        ft, off = dtype.fields[name][:2]
        out[name] = (off, ft.itemsize, ft.kind)
        if ft.kind in ("i", "u", "f") and ft.itemsize > 1:
            # numpy canonicalizes '<' to '=' on LE hosts, so only the
            # EFFECTIVE order is observable here; the wire is LE.
            if ft.byteorder == ">" or (
                    ft.byteorder == "=" and sys.byteorder != "little"):
                vs.append(Violation(
                    "abi/endianness", f"dtype field {name}",
                    f"multi-byte field is effectively big-endian "
                    f"({ft.byteorder!r} on a {sys.byteorder}-endian "
                    f"host); wire contract is little-endian"))
    return out, dtype.itemsize, vs


def ctypes_layout(cls) -> tuple[dict[str, tuple[int, int, str]], int]:
    out: dict[str, tuple[int, int, str]] = {}
    for name, typ in cls._fields_:
        d = getattr(cls, name)
        if issubclass(typ, ctypes.Array):
            kind = "S" if typ._type_ is ctypes.c_char else "V"
        elif typ in (ctypes.c_float, ctypes.c_double):
            kind = "f"
        else:
            kind = "u" if ctypes.sizeof(typ) and typ(-1).value != -1 \
                else "i"
        out[name] = (d.offset, d.size, kind)
    return out, ctypes.sizeof(cls)


def check_struct_formats(sources=None) -> list[Violation]:
    """Every struct.pack/unpack/Struct format literal in the package
    must carry an explicit byte order ('<' — the wire is LE; '@'/bare
    formats add platform padding). `sources` injectable for tests."""
    vs: list[Violation] = []
    if sources is None:
        sources = load_sources([""], root=PKG_ROOT)
    _FMT_FNS = ("Struct", "pack", "pack_into", "unpack", "unpack_from",
                "iter_unpack", "calcsize")
    for src in sources:
        # `from struct import Struct, pack` spellings count too — the
        # rule is package-wide, not spelled-one-way.
        aliases = {
            a.asname or a.name
            for n in ast.walk(src.tree)
            if isinstance(n, ast.ImportFrom) and n.module == "struct"
            for a in n.names if a.name in _FMT_FNS
        }
        for n in ast.walk(src.tree):
            if not isinstance(n, ast.Call):
                continue
            d = dotted(n.func) or ""
            bare = isinstance(n.func, ast.Name) and n.func.id in aliases
            if not (bare or d == "struct.Struct"
                    or d.startswith("struct.pack")
                    or d.startswith("struct.unpack")
                    or d in ("struct.calcsize", "struct.iter_unpack")):
                continue
            if not n.args or not isinstance(n.args[0], ast.Constant) \
                    or not isinstance(n.args[0].value, str):
                continue
            fmt = n.args[0].value
            if not fmt.startswith("<"):
                vs.append(Violation(
                    "abi/format-endianness", site(src, n),
                    f"struct format {fmt!r} lacks explicit '<' — "
                    f"native alignment/order is not the wire contract"))
    return vs


def run() -> list[Violation]:
    import numpy as np  # noqa: F401  (dtype import below needs numpy)

    from matching_engine_tpu import native as native_mod
    from matching_engine_tpu.domain import oprec

    vs: list[Violation] = []
    gwop_h = (REPO_ROOT / "native" / "me_gwop.h").read_text()
    me_native_cpp = (REPO_ROOT / "native" / "me_native.cpp").read_text()

    # 1. MeOpRec (header) vs OPREC_DTYPE (batch-edge wire format).
    cf, csz = c_layout(parse_struct(gwop_h, "MeOpRec"))
    pf, psz, evs = dtype_layout(oprec.OPREC_DTYPE)
    vs += evs
    vs += compare_layouts("native/me_gwop.h:MeOpRec", cf, csz,
                          "domain/oprec.py:OPREC_DTYPE", pf, psz)
    if psz != oprec.RECORD_SIZE:
        vs.append(Violation(
            "abi/total-size", "domain/oprec.py",
            f"RECORD_SIZE {oprec.RECORD_SIZE} != dtype itemsize {psz}"))

    # 2. MeGwOp (header) vs the ctypes ring-record mirror.
    cf, csz = c_layout(parse_struct(gwop_h, "MeGwOp"))
    pf, psz = ctypes_layout(native_mod.MeGwOp)
    vs += compare_layouts("native/me_gwop.h:MeGwOp", cf, csz,
                          "native/__init__.py:MeGwOp", pf, psz)

    # 2b. MeShmResp (header) vs BOTH python mirrors: the shm ingress
    # response record (dtype for vectorized client decode, ctypes for
    # the poller's response builder).
    cf, csz = c_layout(parse_struct(gwop_h, "MeShmResp"))
    pf, psz, evs = dtype_layout(oprec.SHM_RESP_DTYPE)
    vs += evs
    vs += compare_layouts("native/me_gwop.h:MeShmResp", cf, csz,
                          "domain/oprec.py:SHM_RESP_DTYPE", pf, psz)
    pf, psz = ctypes_layout(native_mod.MeShmResp)
    vs += compare_layouts("native/me_gwop.h:MeShmResp", cf, csz,
                          "native/__init__.py:MeShmResp", pf, psz)

    # 3. MeOp (me_native.cpp) vs the ctypes lane-op mirror.
    cf, csz = c_layout(parse_struct(me_native_cpp, "MeOp"))
    pf, psz = ctypes_layout(native_mod.MeOp)
    vs += compare_layouts("native/me_native.cpp:MeOp", cf, csz,
                          "native/__init__.py:MeOp", pf, psz)

    # 4. Explicit-endianness struct formats package-wide.
    vs += check_struct_formats()
    return vs
