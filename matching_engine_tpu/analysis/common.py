"""Shared plumbing for the static-analysis suite.

Every analyzer produces `Violation` rows with a stable (rule, where,
detail) shape so the CLI, the check.sh gate, and the self-tests consume
one vocabulary. Analyzers are pure functions over parsed sources — no
imports of the code under analysis except where a layout is only
knowable by construction (the ABI checker imports the numpy dtype and
the ctypes mirrors, which are import-safe by design).
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib

REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]
PKG_ROOT = REPO_ROOT / "matching_engine_tpu"


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str      # stable rule id, e.g. "lock-order/inversion"
    where: str     # "path:line" (repo-relative) or a logical site
    detail: str    # one-line human explanation

    def __str__(self) -> str:  # the check.sh / CLI line format
        return f"{self.rule}: {self.where}: {self.detail}"


@dataclasses.dataclass
class Source:
    """One parsed python module."""

    path: pathlib.Path
    text: str
    tree: ast.Module

    @property
    def rel(self) -> str:
        try:
            return str(self.path.relative_to(REPO_ROOT))
        except ValueError:
            return str(self.path)

    @property
    def modname(self) -> str:
        """Fully-qualified dotted module name — UNIQUE per file.
        (`path.stem` alone would collapse every package __init__.py
        into one colliding module identity, silently merging their
        function summaries.)"""
        rel = self.rel
        if rel.endswith(".py"):
            rel = rel[:-3]
        return rel.replace("/", ".")


_CACHE: dict[tuple, list[Source]] = {}


def load_sources(dirs, root: pathlib.Path = PKG_ROOT) -> list[Source]:
    """Parse every .py file under the given package-relative dirs (or a
    single file name). Deterministic order (sorted paths) — analyzer
    output feeds generated docs, which must be reproducible. Memoized
    per (dirs, root): run_all and the tier-1 tests walk the same tree
    several times per process, and the tree does not change mid-run."""
    key = (tuple(dirs), str(root))
    if key in _CACHE:
        return _CACHE[key]
    out: list[Source] = []
    for d in dirs:
        p = root / d
        files = [p] if p.is_file() else sorted(p.rglob("*.py"))
        for f in files:
            if "__pycache__" in f.parts:
                continue
            text = f.read_text()
            out.append(Source(f, text, ast.parse(text, filename=str(f))))
    _CACHE[key] = out
    return out


def site(src: Source, node: ast.AST) -> str:
    return f"{src.rel}:{getattr(node, 'lineno', 0)}"


def call_name(node: ast.Call) -> str | None:
    """The rightmost name of a call target: foo() -> "foo",
    a.b.foo() -> "foo". None for computed targets like fns[i]()."""
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def receiver_name(node: ast.Call) -> str | None:
    """The receiver attribute/name a method call goes through:
    self.hub.publish() -> "hub", seq.stamp() -> "seq",
    self.observe() -> "self". None for bare-name calls foo()."""
    f = node.func
    if not isinstance(f, ast.Attribute):
        return None
    v = f.value
    if isinstance(v, ast.Attribute):   # self.<attr>.method() / a.b.method()
        return v.attr
    if isinstance(v, ast.Name):        # <name>.method()
        return v.id
    return None


def dotted(node: ast.AST) -> str | None:
    """Render an attribute chain: jax.experimental.shard_map ->
    "jax.experimental.shard_map". None when any link is computed."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None
