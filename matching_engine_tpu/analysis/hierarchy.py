"""The declared lock hierarchy — the single source of truth the
lock-order analyzer checks the extracted acquisition graph against.

This file is *reviewed configuration*, not code: when you add a lock or
a new nesting, declare it here (and regenerate docs/CONCURRENCY.md via
`python -m matching_engine_tpu.analysis render-concurrency`) or the
analyzer fails tier-1. The rules it encodes are the ones each of which
was the site of a real bug caught late in review:

- the hub lock (StreamHub._lock) is the serialization point every
  serving lane's publish path funnels through; the sequencer and
  auditor locks nest INSIDE it, never the other way;
- nothing reachable while holding the hub lock may touch SQLite or
  materialize protos (the subscriber-gated drop-copy fan-out is the one
  reviewed waiver below) — a blocked publish stalls every lane;
- the auditor's probe lock serializes PROBERS only and is taken
  OUTSIDE the auditor lock, so the hub→auditor publish path can never
  wait on a SQL probe;
- every lock acquisition is `with`-scoped (no bare .acquire() without a
  try/finally release).
"""

from __future__ import annotations

# -- lock identities ---------------------------------------------------------
#
# level name -> the (Class.attr | module.attr) spellings that are this
# logical lock. Subclasses that touch an inherited lock attribute list
# their own spelling too (the analyzer keys sites by the enclosing
# class it can see).

LEVELS: dict[str, tuple[str, ...]] = {
    "hub": ("StreamHub._lock",),
    "sequencer": ("FeedSequencer._lock",),
    "auditor": ("InvariantAuditor._lock",),
    "auditor_probe": ("InvariantAuditor._probe_lock",),
    "store": ("Storage._lock",),
    # Two distinct locks: the spilling wrapper legitimately holds its
    # own lock while handing off to the inner async sink.
    "sink_spill": ("SpillingSink._lock",),
    "sink": ("AsyncStorageSink._lock",),
    "dispatch": ("EngineRunner._dispatch_lock",
                 "NativeLanesRunner._dispatch_lock"),
    "snapshot": ("EngineRunner._snapshot_lock",
                 "NativeLanesRunner._snapshot_lock"),
    "id": ("EngineRunner._id_lock", "NativeLanesRunner._id_lock"),
    "owner_flush": ("EngineRunner._owner_flush_lock",
                    "NativeLanesRunner._owner_flush_lock"),
    "gw_stream": ("GatewayBridge._stream_lock",),
}

# -- the declared partial order ---------------------------------------------
#
# (outer, inner): holding `outer`, acquiring `inner` is legal. The
# analyzer takes the transitive closure; an extracted edge that
# contradicts the closure is an INVERSION, an edge between two declared
# levels that appears in neither direction is UNDECLARED (declare it
# here, deliberately, or restructure the code). Locks not named in
# LEVELS are tracked for the graph/doc and cycle check only.

ORDER: tuple[tuple[str, str], ...] = (
    # The publish funnel: every serving lane serializes through the hub;
    # stamping (sequencer) and online surveillance (auditor) nest inside
    # so stamp order == delivery order == audit order across K lanes.
    ("hub", "sequencer"),
    ("hub", "auditor"),
    # Probers (sink-commit hook vs audit-pump cadence) serialize on the
    # probe lock FIRST, then report verdicts under the auditor lock —
    # SQL itself runs between the two, under probe only.
    ("auditor_probe", "auditor"),
    # The dispatch path: one dispatch at a time; the device-commit
    # snapshot and the oid/symbol directory nest inside it. The auction
    # path publishes its results while still holding the dispatch lock
    # (all-or-nothing fan-out), so the whole publish funnel nests here.
    ("dispatch", "snapshot"),
    ("dispatch", "id"),
    ("dispatch", "hub"),
    ("dispatch", "auditor_probe"),
    # Checkpointing quiesces dispatches, then walks the directory and
    # reads the store.
    ("dispatch", "store"),
    ("dispatch", "owner_flush"),
    ("owner_flush", "store"),
    ("owner_flush", "id"),
    # Recovery/restore paths snapshot the directory while reading rows.
    ("id", "store"),
    # The async sink's queue lock guards handoff only; the flush thread
    # takes store inside it when draining synchronously. The spilling
    # wrapper hands off to the inner sink under its own lock.
    ("sink_spill", "sink"),
    ("sink", "store"),
)

# -- effects forbidden while holding a lock ---------------------------------
#
# level -> effect kinds that must not be reachable (lexically or through
# any resolvable call chain) while the lock is held.
#   "sqlite": any sqlite3 connection/cursor call
#   "proto":  pb2 message construction (proto materialization)

FORBIDDEN_UNDER: dict[str, tuple[str, ...]] = {
    "hub": ("sqlite", "proto"),
    # The hub-locked publish path feeds the auditor inline: SQL under
    # the auditor lock would stall every publishing lane (probes run
    # under auditor_probe only — PR 8's review rule, now enforced).
    "auditor": ("sqlite",),
    "snapshot": ("sqlite",),   # the device step holds it; never block on IO
}

# -- reviewed waivers --------------------------------------------------------
#
# (rule, holder_level, reached_function_or_site) triples the review
# explicitly accepted, each with a justification. Keep this list SHORT:
# a waiver is a documented debt, not an escape hatch.

WAIVERS: frozenset[tuple[str, str, str]] = frozenset({
    # Drop-copy fan-out: wire events for LIVE audit subscribers
    # materialize inside the hub lock by design — stamping and fan-out
    # must be atomic across K publishing lanes, and the subscriber-less
    # steady state never enters this branch (PR 8; the retained form is
    # the row chunk, protos are copy-on-replay).
    ("lock-order/forbidden-effect", "hub", "materialize_chunk"),
})

# -- receiver typing for call resolution ------------------------------------
#
# Attribute/variable name -> the analyzed class it holds, None for
# external types the analyzer must not resolve into (their methods
# never take tracked locks), or "sqlite3" for DB handles (calls through
# them ARE the sqlite effect).

ATTR_TYPES: dict[str, str | None] = {
    "hub": "StreamHub",
    "stream_hub": "StreamHub",
    "sequencer": "FeedSequencer",
    "auditor": "InvariantAuditor",
    "storage": "Storage",
    "store": "Storage",
    "sink": "AsyncStorageSink",
    "_inner": "AsyncStorageSink",   # SpillingSink wraps the async sink
    "dom": "RetransmissionRing",    # feed replay's per-domain ring
    "runner": "EngineRunner",
    "dispatcher": "BatchDispatcher",
    "publisher": "DropCopyPublisher",
    "pump": "AuditPump",
    "conn": "sqlite3",
    "_conn": "sqlite3",
    "cur": "sqlite3",
    "cursor": "sqlite3",
    # External leaves: their methods never acquire tracked locks, and
    # several share method names with analyzed classes (Metrics.observe
    # vs InvariantAuditor.observe).
    "metrics": None,
    "q": None,
    "queue": None,
    "logger": None,
    "tracer": None,
    "recorder": None,
}

# -- callback bindings -------------------------------------------------------
#
# Calls through a bare parameter name the analyzer cannot resolve
# statically, bound to their one real production target. The hub's
# `observer` hook is how the auditor consumes delivered seqs INSIDE the
# hub lock (stamp order across lanes) — the binding makes the
# hub->auditor edge visible to the graph instead of invisible behind a
# closure.

CALLBACK_BINDINGS: dict[str, tuple[str, ...]] = {
    "observer": ("InvariantAuditor.observe_rows",),
}
