"""The declared lock hierarchy — the single source of truth the
lock-order analyzer checks the extracted acquisition graph against.

This file is *reviewed configuration*, not code: when you add a lock or
a new nesting, declare it here (and regenerate docs/CONCURRENCY.md via
`python -m matching_engine_tpu.analysis render-concurrency`) or the
analyzer fails tier-1. The rules it encodes are the ones each of which
was the site of a real bug caught late in review:

- the hub lock (StreamHub._lock) is the serialization point every
  serving lane's publish path funnels through; the sequencer and
  auditor locks nest INSIDE it, never the other way;
- nothing reachable while holding the hub lock may touch SQLite or
  materialize protos (the subscriber-gated drop-copy fan-out is the one
  reviewed waiver below) — a blocked publish stalls every lane;
- the auditor's probe lock serializes PROBERS only and is taken
  OUTSIDE the auditor lock, so the hub→auditor publish path can never
  wait on a SQL probe;
- every lock acquisition is `with`-scoped (no bare .acquire() without a
  try/finally release).
"""

from __future__ import annotations

# -- lock identities ---------------------------------------------------------
#
# level name -> the (Class.attr | module.attr) spellings that are this
# logical lock. Subclasses that touch an inherited lock attribute list
# their own spelling too (the analyzer keys sites by the enclosing
# class it can see).

LEVELS: dict[str, tuple[str, ...]] = {
    "hub": ("StreamHub._lock",),
    "sequencer": ("FeedSequencer._lock",),
    "auditor": ("InvariantAuditor._lock",),
    "auditor_probe": ("InvariantAuditor._probe_lock",),
    "store": ("Storage._lock",),
    # Two distinct locks: the spilling wrapper legitimately holds its
    # own lock while handing off to the inner async sink.
    "sink_spill": ("SpillingSink._lock",),
    "sink": ("AsyncStorageSink._lock",),
    "dispatch": ("EngineRunner._dispatch_lock",
                 "NativeLanesRunner._dispatch_lock"),
    "snapshot": ("EngineRunner._snapshot_lock",
                 "NativeLanesRunner._snapshot_lock"),
    "id": ("EngineRunner._id_lock", "NativeLanesRunner._id_lock"),
    "owner_flush": ("EngineRunner._owner_flush_lock",
                    "NativeLanesRunner._owner_flush_lock"),
    "gw_stream": ("GatewayBridge._stream_lock",),
    # Warm-standby replication (matching_engine_tpu/replication/):
    # repl_promote serializes the standby->primary transition (one
    # winner; concurrent Promote RPC / heartbeat-lapse callers wait),
    # repl_pair guards the attestation pairing stores + the in-progress
    # primary record group. Comparison and flight-dump run OUTSIDE
    # repl_pair — a slow dump must not stall the applier or attestor.
    "repl_promote": ("StandbyReplica._lock",),
    "repl_pair": ("StandbyReplica._attest_lock",),
    # Vectorized admission screens (server/admission.py): one batch-
    # granular lock serializes the screen state (rate windows, price
    # anchors, STP tables) across every ingress thread (rpc handlers,
    # the shm poller, the gateway bridge's forwarded batch). Nothing
    # nests inside it — the lock body is numpy passes + dict updates.
    "admission": ("AdmissionScreens._lock",),
    # Feed fan-in (feed/fanin.py, --feed-fanin merged): each lane's
    # publisher lock makes the (lane_seq++, enqueue) pair atomic — the
    # merger's contiguity check depends on queue order == seq order per
    # lane. A leaf: the body is an increment and a Queue.put.
    "fanin_lane": ("LaneFeedPublisher._lock",),
    # The cross-lane auction barrier (server/shards.py): each lane's
    # barrier worker votes under this lock while HOLDING its own lane's
    # dispatch lock — the one sanctioned cross-lane rendezvous. A leaf:
    # the body mutates vote counters and sets an Event.
    "barrier": ("_AuctionBarrier._lock",),
}

# -- the declared partial order ---------------------------------------------
#
# (outer, inner): holding `outer`, acquiring `inner` is legal. The
# analyzer takes the transitive closure; an extracted edge that
# contradicts the closure is an INVERSION, an edge between two declared
# levels that appears in neither direction is UNDECLARED (declare it
# here, deliberately, or restructure the code). Locks not named in
# LEVELS are tracked for the graph/doc and cycle check only.

ORDER: tuple[tuple[str, str], ...] = (
    # The publish funnel: every serving lane serializes through the hub;
    # stamping (sequencer) and online surveillance (auditor) nest inside
    # so stamp order == delivery order == audit order across K lanes.
    ("hub", "sequencer"),
    ("hub", "auditor"),
    # Probers (sink-commit hook vs audit-pump cadence) serialize on the
    # probe lock FIRST, then report verdicts under the auditor lock —
    # SQL itself runs between the two, under probe only.
    ("auditor_probe", "auditor"),
    # The dispatch path: one dispatch at a time; the device-commit
    # snapshot and the oid/symbol directory nest inside it. The auction
    # path publishes its results while still holding the dispatch lock
    # (all-or-nothing fan-out), so the whole publish funnel nests here.
    ("dispatch", "snapshot"),
    ("dispatch", "id"),
    ("dispatch", "hub"),
    ("dispatch", "auditor_probe"),
    # Checkpointing quiesces dispatches, then walks the directory and
    # reads the store.
    ("dispatch", "store"),
    ("dispatch", "owner_flush"),
    ("owner_flush", "store"),
    ("owner_flush", "id"),
    # Recovery/restore paths snapshot the directory while reading rows.
    ("id", "store"),
    # The async sink's queue lock guards handoff only; the flush thread
    # takes store inside it when draining synchronously. The spilling
    # wrapper hands off to the inner sink under its own lock.
    ("sink_spill", "sink"),
    ("sink", "store"),
    # Merged feed fan-in: the runner/dispatcher publish tail (still under
    # the dispatch lock on the auction path) enqueues through the lane
    # publisher's leaf lock instead of the hub.
    ("dispatch", "fanin_lane"),
    # Cross-lane auction barrier: run_auction_phased votes (barrier lock)
    # while holding ITS OWN lane's dispatch lock. K workers each hold a
    # DIFFERENT dispatch-lock instance, so the shared barrier lock is the
    # only cross-lane acquisition — no cycle is expressible.
    ("dispatch", "barrier"),
)

# -- effects forbidden while holding a lock ---------------------------------
#
# level -> effect kinds that must not be reachable (lexically or through
# any resolvable call chain) while the lock is held.
#   "sqlite": any sqlite3 connection/cursor call
#   "proto":  pb2 message construction (proto materialization)

FORBIDDEN_UNDER: dict[str, tuple[str, ...]] = {
    "hub": ("sqlite", "proto"),
    # The hub-locked publish path feeds the auditor inline: SQL under
    # the auditor lock would stall every publishing lane (probes run
    # under auditor_probe only — PR 8's review rule, now enforced).
    "auditor": ("sqlite",),
    "snapshot": ("sqlite",),   # the device step holds it; never block on IO
}

# -- reviewed waivers --------------------------------------------------------
#
# (rule, holder_level, reached_function_or_site) triples the review
# explicitly accepted, each with a justification. Keep this list SHORT:
# a waiver is a documented debt, not an escape hatch.

WAIVERS: frozenset[tuple[str, str, str]] = frozenset({
    # Drop-copy fan-out: wire events for LIVE audit subscribers
    # materialize inside the hub lock by design — stamping and fan-out
    # must be atomic across K publishing lanes, and the subscriber-less
    # steady state never enters this branch (PR 8; the retained form is
    # the row chunk, protos are copy-on-replay).
    ("lock-order/forbidden-effect", "hub", "materialize_chunk"),
})

# -- receiver typing for call resolution ------------------------------------
#
# Attribute/variable name -> the analyzed class it holds, None for
# external types the analyzer must not resolve into (their methods
# never take tracked locks), or "sqlite3" for DB handles (calls through
# them ARE the sqlite effect).

ATTR_TYPES: dict[str, str | None] = {
    "hub": "StreamHub",
    "stream_hub": "StreamHub",
    "sequencer": "FeedSequencer",
    "auditor": "InvariantAuditor",
    "storage": "Storage",
    "store": "Storage",
    "sink": "AsyncStorageSink",
    "_inner": "AsyncStorageSink",   # SpillingSink wraps the async sink
    "dom": "RetransmissionRing",    # feed replay's per-domain ring
    "runner": "EngineRunner",
    "dispatcher": "BatchDispatcher",
    "publisher": "DropCopyPublisher",
    "pump": "AuditPump",
    "replica": "StandbyReplica",
    "oplog": "OpLogShipper",
    "sub": "_Subscription",         # stream fan-out subscriptions
    "admission": "AdmissionScreens",
    # The shm ring wrapper: its methods are ctypes crossings into
    # me_shmring.cpp, never tracked-lock acquisitions.
    "ring": None,
    "conn": "sqlite3",
    "_conn": "sqlite3",
    "cur": "sqlite3",
    "cursor": "sqlite3",
    # External leaves: their methods never acquire tracked locks, and
    # several share method names with analyzed classes (Metrics.observe
    # vs InvariantAuditor.observe).
    "metrics": None,
    "fanin": "FeedFanIn",
    "_fanin": "FeedFanIn",
    "_real_hub": "StreamHub",       # LaneFeedPublisher's delegation target
    "barrier": "_AuctionBarrier",
    "q": None,
    "queue": None,
    "logger": None,
    "tracer": None,
    "recorder": None,
}

# -- thread roles ------------------------------------------------------------
#
# role -> the entry points that run on that kind of thread. An entry is
# "Class.method" (or "Class.*" for every method), or
# "<module-basename>.function". The lockset analyzer (analysis/lockset.py)
# propagates roles through the resolvable call graph; shared state
# reachable from two roles must have a non-empty lockset intersection or
# a declared OWNERSHIP policy. Every `Thread(target=...)` spawn in the
# scanned tree must resolve to one of these entries (or be an external
# callable) — an undeclared spawn fails the lockset/undeclared-thread-root
# rule so this table cannot rot.

THREAD_ROLES: dict[str, tuple[str, ...]] = {
    # gRPC handler threads (grpcio pool) + the C++ gateway's forwarded
    # verbs, which call the same service handlers.
    "rpc": ("MatchingEngineService.*",),
    # Boot/shutdown: build_server wiring, recovery replay, signal-driven
    # teardown. Writes made here happen before the serving threads spawn
    # (init-before-spawn handoff).
    "main": ("main.build_server", "main.main", "main.shutdown",
             "main.recover_books", "main._boot_runner"),
    # The dispatcher drain / lane threads (one per serving lane).
    "dispatch": ("BatchDispatcher._run", "LaneRingDispatcher._run",
                 "NativeRingDispatcher._run"),
    # The C++ gateway bridge: ring drain, unary forward workers, and
    # per-stream threads.
    "gateway": ("GatewayBridge._run", "GatewayBridge._run_native",
                "GatewayBridge._worker", "GatewayBridge._stream"),
    # The async storage sink flusher.
    "sink": ("AsyncStorageSink._run",),
    # The out-of-band audit pump (drop-copy build/stamp/invariants).
    "audit_pump": ("AuditPump._run",),
    # The feed spill flusher (segment writes off the publish path).
    "feed_spill": ("FeedSequencer._flush_loop",),
    # The periodic checkpoint daemon.
    "checkpoint": ("CheckpointDaemon._run",),
    # The shard balance sampler.
    "sampler": ("ServingShards._sample_loop",),
    # The metrics/scrape HTTP server (ThreadingHTTPServer handlers).
    "scrape": ("Handler.do_GET",),
    # The trace-export background writer.
    "trace_writer": ("TraceExporter._run",),
    # Flight-recorder dump threads (SIGUSR2 / dispatch-error).
    "flight_dump": ("FlightRecorder.dump",),
    # Warm-standby replication (matching_engine_tpu/replication/). The
    # primary's op-log heartbeat publisher (dispatch shipping itself runs
    # on the drain loops — the dispatch role).
    "oplog_ship": ("OpLogShipper._heartbeat_loop",),
    # The standby's receive loop: SequencedSubscriber over the primary's
    # oplog channel, resume/gap-fill, liveness stamping.
    "repl_rx": ("StandbyReplica._rx_loop",),
    # The standby's applier: one engine dispatch per oplog event, then
    # the same sink/hub/drop-copy publish path a primary drain loop runs.
    "repl_apply": ("StandbyReplica._applier_loop",),
    # The attestor: drop-copy audit subscriber pairing primary records
    # with locally produced rows per dispatch trace.
    "repl_attest": ("StandbyReplica._attestor_loop",),
    # The promotion watcher: heartbeat-age gauge, idle attestation-group
    # flush, and the opt-in auto-promote trigger.
    "repl_watch": ("StandbyReplica._watcher_loop",),
    # The shared-memory ingress poller (server/shm_ingress.py): pops
    # committed record runs from the shm ring (ring v2: N registered
    # writer lanes fan into one ring; commit words carry the lane id),
    # screens them through the service's shared batch pipeline
    # (admission + routing + dispatch), accounts per-writer admit/reject
    # series off the commit-stamped lane column, and answers through the
    # response ring's per-lane demux cursors. Single consumer by
    # design — the multi-producer side lives in native/me_shmring.cpp
    # (lock-free claim CAS), not in python threads.
    "shm_poller": ("ShmIngress._run",),
    # The merged feed fan-in's single merger (feed/fanin.py): drains the
    # K lanes' publish queue, enforces per-lane seq contiguity, delivers
    # into the real hub — the only thread contending for the hub lock in
    # merged mode.
    "feed_merger": ("FeedFanIn._run",),
    # Cross-lane auction barrier workers (server/shards.py): one per
    # lane for the all-symbols uncross, each driving its OWN lane's
    # run_auction_phased and voting into the two-phase barrier. (The
    # device-sweep bench observes the booted server from outside the
    # scanned tree; its in-server sampling is the "sampler" role.)
    "auction_barrier": ("ServingShards._barrier_lane",),
}

# -- shared-state ownership --------------------------------------------------
#
# "Class.attr" / "module.name" -> (policy, witness). The lockset analyzer
# flags cross-thread-reachable state whose access locksets have an empty
# intersection; an entry here is the REVIEWED exception, and each policy
# is still machine-checked:
#
#   "single-writer"    exactly one role writes (others only read a
#                      monotonic/atomic snapshot) — two writing roles
#                      turn the entry into lockset/ownership-violation;
#   "init-before-spawn" writes happen only on the main (boot) role
#                      before the serving threads exist — a write from
#                      any other role violates. Declarative: while the
#                      contract holds nothing flags (boot writes are
#                      non-concurrent), so these entries are exempt
#                      from the stale-waiver rule;
#   "gil-atomic"       single CPython bytecode container ops (deque
#                      append/popleft, list append, dict store) relied
#                      on as atomic by contract — reviewed, with the
#                      witness naming where the contract is documented.
#
# Keep entries SHORT and witnessed: this is documented debt, not an
# escape hatch. The analyzer also flags entries that stopped matching
# any flagged location (lockset/unused-ownership) so the table cannot
# accrete stale waivers.

OWNERSHIP: dict[str, tuple[str, str]] = {
    # Per-dispatch stage ledger: each DispatchTimeline belongs to the one
    # drain loop that created it and travels with its dispatch; the roles
    # the analyzer sees share the CLASS, never an instance.
    "DispatchTimeline.t_publish": (
        "instance-confined",
        "obs.DispatchTimeline — created per dispatch by one drain loop; "
        "stamps happen on that loop (or under the dispatch lock)"),
    "DispatchTimeline.t_build": (
        "instance-confined",
        "obs.DispatchTimeline — same per-dispatch confinement as "
        "t_publish (the standby applier is just one more creating loop)"),
    "DispatchTimeline.t_issue": (
        "instance-confined",
        "obs.DispatchTimeline — same per-dispatch confinement as "
        "t_publish"),
    # Reusable pop buffer on the native ring wrappers: one per
    # dispatcher, touched only by that dispatcher's drain thread.
    "LaneRing._buf": (
        "instance-confined",
        "native.LaneRing.pop_batch_raw — one ring per LaneRingDispatcher, "
        "popped only by its drain thread"),
    "NativeRing._buf": (
        "instance-confined",
        "native.NativeRing.pop_batch — one ring per NativeRingDispatcher, "
        "popped only by its drain thread"),
    "NativeGateway._buf": (
        "instance-confined",
        "native.NativeGateway.pop_batch — popped only by the gateway "
        "bridge's drain thread"),
    # Auction-mode dirty flag: set_auction_mode writes value-then-dirty
    # lock-free (it may run under the dispatch lock; SQLite must not);
    # flushers serialize on _owner_flush_lock and clear dirty BEFORE
    # reading the value, so a concurrent flip re-marks and re-persists.
    "EngineRunner._mode_dirty": (
        "gil-atomic",
        "engine_runner.flush_auction_mode — clear-before-read protocol, "
        "pinned by test_flush_auction_mode_concurrent_flip"),
    # Auction-mode flag: flips happen on the RunAuction path (rpc /
    # gateway) — set_auction_mode is documented lock-free because it may
    # run under the dispatch lock; the drop-copy publisher samples the
    # bool GIL-atomically to stamp envelopes and tolerates a one-flip-
    # stale read (the dispatch path re-checks the mode under its own
    # lock before gating submits).
    "EngineRunner.auction_mode": (
        "gil-atomic",
        "engine_runner.set_auction_mode — \"persistence happens in "
        "flush_auction_mode, OUTSIDE the dispatch lock\"; sampled by "
        "dropcopy.publish for the in_auction envelope bit"),
    # Device-step state touched from the dispatch_{sparse,dense,mega}
    # closures: run_pipelined executes them strictly under the dispatch
    # lock (_stage_locked/_finish_*_locked build and drive them), but
    # the analyzer's closure rule deliberately drops lock context ("a
    # closure runs on some caller's thread later") — the standby applier
    # reaching run_dispatch made these the first role-visible writes.
    # The reviewed fact: every writer holds EngineRunner._dispatch_lock.
    "EngineRunner._step_num": (
        "gil-atomic",
        "engine_runner._prepare dispatch closures — executed by "
        "run_pipelined under the dispatch lock (closure-approximation "
        "false positive; PR 11 review)"),
    "EngineRunner.pending_recon": (
        "gil-atomic",
        "engine_runner._ledger_lost — called from decode under the "
        "dispatch lock via the _prepare closures (closure-approximation "
        "false positive; PR 11 review)"),
    # Subscriber-gated proto-build flag: refreshed at the top of every
    # dispatch/auction (under the dispatch lock on the serving paths)
    # from the hub's documented lock-free peek; a one-dispatch-stale
    # read only builds (or skips) protos for subscribers that attached
    # or left mid-dispatch — the same contract as StreamHub._ou_subs.
    "EngineRunner._build_ou": (
        "gil-atomic",
        "engine_runner._stage_locked/run_auction — single bool refreshed "
        "per dispatch from streams.has_order_update_subs (the documented "
        "lock-free peek); readers tolerate one-dispatch staleness"),
    # Order directories: every WRITE happens under the dispatch lock
    # (registration in _decode_batch / eviction in _evict, both inside
    # the locked decode); the lock-free dict probes from the RPC edge
    # (CancelOrder/AmendOrder/lane_for_order "id-residue-then-directory-
    # probe", PR 4) and the standby applier's target lookup are the
    # documented GIL-atomic read contract — a stale probe answers like a
    # request that arrived one dispatch earlier, and the dispatch itself
    # re-validates under its own lock.
    "EngineRunner.orders_by_id": (
        "gil-atomic",
        "service.CancelOrder/AmendOrder + standby._apply_dispatch — "
        "documented lock-free directory probe (PR 4); all writes under "
        "the dispatch lock in the decode path"),
    "EngineRunner.orders_by_handle": (
        "gil-atomic",
        "engine_runner._decode_batch/_evict — writes under the dispatch "
        "lock via the _prepare closures (closure-approximation false "
        "positive; PR 11 review)"),
    "EngineRunner.pending_owner_ids": (
        "gil-atomic",
        "engine_runner owner-id assignment appends under the id lock on "
        "the decode path; flush_owner_ids drains under _owner_flush_lock "
        "(closure-approximation false positive; PR 11 review)"),
    # Dispatch counter: incremented on the (locked) commit path, sampled
    # lock-free by the shard balance sampler — a stale single-int read
    # only skews one cadence of the lane_dispatch_rate gauge.
    "EngineRunner.ops_dispatched": (
        "gil-atomic",
        "shards.ServingShards._sample_loop — monotonic rate sampling, "
        "staleness bounded by the sample cadence"),
    # Probe-due flag: observe_rows (hub-locked) sets it, the pump tests
    # and clears it; a missed clear re-probes one cadence later, a
    # missed set probes at the next notify_commit — both harmless.
    "InvariantAuditor._probe_due": (
        "gil-atomic",
        "auditor._observe_locked — \"just sets a flag the pump resolves "
        "post-publish\" (PR 8 review)"),
    # TTL book cache: plain dict get/pop/store, deliberately unlocked;
    # the eviction loop already treats a concurrently-mutated iterator
    # as someone else's eviction.
    "MatchingEngineService._book_cache": (
        "gil-atomic",
        "service.GetOrderBook — bounded GIL-atomic dict cache "
        "(--book-cache-ms; PR 6)"),
    # Single-shot fault injector (tests/soak corruption round): armed
    # once, fires once; a double-fire race would only inject the fault
    # twice in a corruption test that asserts the auditor catches it.
    "_FaultInjector.after": (
        "gil-atomic", "dropcopy._FaultInjector — test-only single-shot"),
    "_FaultInjector.fired": (
        "gil-atomic", "dropcopy._FaultInjector — test-only single-shot"),
    # Spill in-flight batches: appended under the sequencer lock,
    # removed by the flusher with GIL-atomic list ops; replay dedups by
    # seq against freshly-written segments (documented in _Spill).
    "_Spill._inflight": (
        "gil-atomic",
        "sequencer._Spill — \"GIL-atomic list ops; the replay merge "
        "dedups by seq\""),
    # Feed epoch: a single int swapped under the sequencer lock exactly
    # once per boot (init) or promotion (rebase_epoch, publishers
    # quiesced first). Lock-free readers (resume staleness checks,
    # /replz snapshots) tolerate one-transition staleness by design — a
    # stale epoch read can only misclassify a resume as cross-epoch,
    # which IS the client-rebase path those readers exist to trigger.
    "FeedSequencer.epoch": (
        "gil-atomic",
        "sequencer.rebase_epoch — write under FeedSequencer._lock with "
        "publishing quiesced (standby.promote step 4); readers are "
        "epoch-inequality checks that tolerate staleness"),
    # Subscriber-table peek: the decode path's has_*_subs reads the dict
    # lock-free to skip proto builds when nobody listens — documented
    # "Lock-free peek" (streams.py): a subscriber attaching mid-dispatch
    # just misses that dispatch, same as attaching a moment later.
    "StreamHub._md_subs": (
        "gil-atomic",
        "streams.has_market_data_subs — documented lock-free peek; "
        "mutations under the hub lock"),
    "StreamHub._ou_subs": (
        "gil-atomic",
        "streams.has_order_update_subs — documented lock-free peek; "
        "mutations under the hub lock"),
    # Warm-standby replica state (replication/standby.py). The rx loop
    # is the only writer of the receive cursors; applier the only writer
    # of the applied cursors; attestor/rx each own their subscriber
    # handle. Readers (watcher cadence, /replz snapshot, promote after
    # quiescing) take monotonic GIL-atomic snapshots.
    "StandbyReplica._rx_seq": (
        "single-writer", "standby._rx_loop — receive cursor; snapshot "
                         "readers tolerate staleness"),
    "StandbyReplica._rx_dispatch_seq": (
        "single-writer", "standby._rx_loop — lag baseline; the applier "
                         "reads a monotonic snapshot"),
    "StandbyReplica._rx_bytes": (
        "single-writer", "standby._rx_loop — lag accounting"),
    "StandbyReplica._last_rx": (
        "single-writer", "standby._rx_loop — liveness stamp; the "
                         "watcher's heartbeat-age read is monotonic"),
    "StandbyReplica._ever_rx": (
        "single-writer", "standby._rx_loop — monotonic bool latch "
                         "(False -> True only); the watcher's "
                         "auto-promote arm check tolerates a one-poll-"
                         "stale False (it refuses, then arms next poll)"),
    "StandbyReplica._rx_sub": (
        "single-writer", "standby._rx_loop — reconnect swaps its own "
                         "subscriber; promote/close only cancel() the "
                         "latest (a stale cancel is re-issued on the "
                         "next loop turn, which sees _stop set)"),
    "StandbyReplica._attest_sub": (
        "single-writer", "standby._attestor_loop — same contract as "
                         "_rx_sub"),
    "StandbyReplica._applied_seq": (
        "single-writer", "standby._apply_dispatch — applied cursor; "
                         "promote reads it after joining the applier"),
    "StandbyReplica._applied_bytes": (
        "single-writer", "standby._apply_dispatch — lag accounting"),
    "StandbyReplica._max_oid": (
        "single-writer", "standby._apply_dispatch — OID floor input; "
                         "promote reads it after joining the applier"),
    # Latches: set-once (or monotonic) flags written by whichever
    # replication thread observes the condition first, read by /replz.
    "StandbyReplica.diverged": (
        "gil-atomic", "standby._compare — monotonic bool latch (False -> "
                      "True only)"),
    "StandbyReplica.poisoned": (
        "gil-atomic", "standby._poison — first-writer-wins string latch "
                      "(checked-then-set; a second writer's reason is "
                      "dropped, the replica is equally dead either way)"),
    "StandbyReplica._promote_started": (
        "gil-atomic", "standby.promote — bool latch swapped under "
                      "repl_promote; the watcher/snapshot read a "
                      "one-transition-stale value at worst"),
    "StandbyReplica.promoted_epoch": (
        "gil-atomic", "standby.promote — written once by the single "
                      "promote winner (started-flag swap under "
                      "repl_promote); losers wait on _promote_done "
                      "before reading"),
    # Subscriber bookkeeping: drops is a monotonic counter bumped by
    # whichever publisher hits the full queue; last_seq is written by
    # the one consumer thread and read by the publisher's lag scan,
    # which tolerates staleness by design.
    "_Subscription.drops": (
        "gil-atomic",
        "streams._Subscription.offer — drop-oldest accounting, "
        "monotonic counter"),
    "_Subscription.last_seq": (
        "instance-confined",
        "streams._Subscription.stream — one consumer thread writes; "
        "_update_lag_locked reads a GIL-atomic snapshot (\"lag can only "
        "shrink while it goes unsampled\")"),
}

# -- declared wall-clock / nondeterminism waivers ----------------------------
#
# (rule, "Class.meth" | "mod.fn", source-token-or-prefix) triples the
# review accepted for the determinism analyzer, each with a witness.
# "*" matches any token. These are the ONLY bytes on the replay
# surfaces allowed to derive from wall clock — the HA replica's
# bit-identity comparisons normalize exactly these fields.

DETERMINISM_WAIVERS: frozenset[tuple[str, str, str]] = frozenset({
    # Drop-copy dispatch envelope: ingress_ts_us is the DECLARED
    # wall-clock edge-ingress stamp (PR 8); parity comparisons normalize
    # the envelope away (tests/test_audit_online.py), so it is outside
    # the replica bit-identity surface.
    ("determinism/wallclock-taint", "dropcopy.dropcopy_events",
     "time.time"),
    # feed_epoch: the per-boot epoch id is wall-clock BY DESIGN (only
    # inequality between boots matters — sequencer.py boot-id comment);
    # a replica stamps its own epoch and clients rebase on mismatch.
    ("determinism/wallclock-taint", "dropcopy.materialize_chunk",
     "time.time"),
    ("determinism/wallclock-taint", "FeedSequencer._stamp", "time.time"),
    # Storage audit timestamps: the ts/updated_at columns are DECLARED
    # wall-clock bookkeeping; the auditor's store probes and the HA
    # store-identity comparison read status/remaining/fills, never ts
    # (scripts/audit.py, auditor._store_probe). Removing the columns
    # would blind the operator's forensic timeline for nothing.
    ("determinism/wallclock-taint", "Storage.add_fill", "time.time_ns"),
    ("determinism/wallclock-taint", "Storage.apply_batch",
     "time.time_ns"),
    ("determinism/wallclock-taint", "Storage.apply_repairs",
     "time.time_ns"),
    ("determinism/wallclock-taint", "Storage.insert_new_order",
     "time.time_ns"),
    ("determinism/wallclock-taint", "Storage.update_order_status",
     "time.time_ns"),
    # Checkpoint meta "ts": operator-facing save time in the sidecar
    # meta dict; restore never reads it (checkpoint._cfg_from_meta).
    ("determinism/wallclock-taint", "checkpoint._atomic_checkpoint_write",
     "time.time"),
    ("determinism/wallclock-taint", "checkpoint.save_checkpoint",
     "time.time"),
    ("determinism/wallclock-taint", "checkpoint._save_checkpoint_hostlocal",
     "time.time"),
    # Slot-keyed TOB dict / touched-orders dict: filled in device decode
    # order by the single dispatch thread, so insertion order IS a
    # deterministic function of the op log; per-symbol feed domains make
    # the cross-symbol interleaving irrelevant to per-domain seq lines.
    ("determinism/unordered-iteration", "<locals>.finalize_sparse", "*"),
    ("determinism/unordered-iteration", "EngineRunner._auction_commit_locked",
     "*"),
})

# -- callback bindings -------------------------------------------------------
#
# Calls through a bare parameter name the analyzer cannot resolve
# statically, bound to their one real production target. The hub's
# `observer` hook is how the auditor consumes delivered seqs INSIDE the
# hub lock (stamp order across lanes) — the binding makes the
# hub->auditor edge visible to the graph instead of invisible behind a
# closure.

CALLBACK_BINDINGS: dict[str, tuple[str, ...]] = {
    "observer": ("InvariantAuditor.observe_rows",),
}
