"""Render docs/CONCURRENCY.md from the declared hierarchy + the
extracted acquisition graph + the lockset analyzer's thread-role and
shared-state view. The committed file must match the regenerated text
byte-for-byte (tier-1 pins it) — the doc can never drift from what the
analyzers actually prove.
"""

from __future__ import annotations

from matching_engine_tpu.analysis import hierarchy, lockorder, lockset
from matching_engine_tpu.analysis.common import REPO_ROOT

_HEADER = """\
# CONCURRENCY — the lock hierarchy, as enforced

> GENERATED FILE — do not edit by hand. Regenerate with
> `python -m matching_engine_tpu.analysis render-concurrency`
> after changing `matching_engine_tpu/analysis/hierarchy.py` or any
> locking code. `tests/test_analysis.py` fails tier-1 when this file
> is stale, and `scripts/check.sh` gates the rules themselves.

Every rule below is *checked statically* by the lock-order analyzer
(`matching_engine_tpu/analysis/lockorder.py`) on every tier-1 run: the
acquisition graph is re-extracted from the AST of `server/`, `feed/`,
`audit/`, `storage/`, `native/` and `utils/checkpoint.py`, and compared
against the declared hierarchy. A new `with <lock>` that nests two
declared locks in an undeclared order fails the build — amending this
hierarchy is a reviewed edit to `analysis/hierarchy.py`, not a comment.

## The rules

- **Declared order only.** Holding lock A while acquiring lock B is
  legal only if A→B is in the declared partial order below (or B is
  untracked). The inverse order anywhere is a deadlock window.
- **Nothing slow under the hub lock.** The hub (`StreamHub._lock`) is
  the one point every serving lane's publish path serializes through:
  SQLite calls and proto materialization are forbidden under it (one
  reviewed waiver: the subscriber-gated drop-copy fan-out, which must
  stamp and deliver atomically).
- **No SQL under the auditor lock.** Store probes connect and query
  under `auditor_probe` only; the hub→auditor publish path never waits
  on SQLite.
- **`with`-scoped locking only.** A bare `.acquire()` without a
  provable `finally: release()` is flagged wholesale.
- **No unguarded shared state.** The lockset analyzer
  (`matching_engine_tpu/analysis/lockset.py`) classifies every shared
  location by the locks held at each access and the thread roles that
  reach it; an empty lockset intersection across roles fails the build
  unless a reviewed ownership policy below covers it.

## Declared levels

| Level | Lock object(s) |
|---|---|
"""

_AMEND = """\

## Amending the hierarchy

1. Add the lock to `LEVELS` in `matching_engine_tpu/analysis/hierarchy.py`
   (one level per *logical* lock; list every class spelling that holds it).
2. Declare its nesting in `ORDER` — think about which existing level it
   must nest inside or outside, and keep the relation a DAG.
3. If a callback hides an edge from the AST (the hub's `observer` hook),
   bind it in `CALLBACK_BINDINGS` so the edge stays visible.
4. A new background thread needs a `THREAD_ROLES` entry (the spawn is
   rejected otherwise); new cross-thread state either takes a lock or
   earns an `OWNERSHIP` entry with a policy and a witness.
5. Run `python -m matching_engine_tpu.analysis render-concurrency` and
   commit the regenerated file together with the code.

A waiver (`WAIVERS`) needs a justification comment and review — it is a
documented debt, not an escape hatch.
"""


def render() -> str:
    graph = lockorder.build_graph()
    out = [_HEADER]
    for level in sorted(hierarchy.LEVELS):
        idents = ", ".join(f"`{i}`" for i in hierarchy.LEVELS[level])
        out.append(f"| `{level}` | {idents} |\n")

    out.append("\n## Declared order (outer → inner)\n\n")
    for a, b in hierarchy.ORDER:
        out.append(f"- `{a}` → `{b}`\n")

    out.append("\n## Extracted acquisition graph (with witnesses)\n\n"
               "Every edge the analyzer currently observes in the tree, "
               "with the first witness site (call chains abbreviated to "
               "their entry point):\n\n")
    lvl_edges: dict[tuple[str, str], str] = {}
    for (h, t), w in sorted(graph.edges.items()):
        key = (lockorder.level_of(h), lockorder.level_of(t))
        lvl_edges.setdefault(key, w)
    for (ha, ta), w in sorted(lvl_edges.items()):
        w0 = w.split(" -> ")[0]
        label = ta.replace("effect:", "⚠ effect: ")
        out.append(f"- `{ha}` → `{label}` — `{w0}`\n")

    out.append("\n## Reviewed waivers\n\n")
    for rule, holder, leaf in sorted(hierarchy.WAIVERS):
        out.append(f"- `{rule}` under `{holder}` reaching `{leaf}` "
                   f"(see the justification in hierarchy.py)\n")

    # -- lockset sections (analysis/lockset.py) -------------------------
    ls_graph = lockset.build_graph()
    contexts = lockset.compute_role_context(ls_graph)
    locations = lockset.collect_locations(ls_graph)
    out.append(
        "\n## Thread roles\n\n"
        "The lockset race analyzer (`analysis/lockset.py`) propagates "
        "these roles from their declared entry points "
        "(`hierarchy.THREAD_ROLES`) through the resolvable call graph; "
        "shared mutable state reachable from two roles must have a "
        "non-empty lockset intersection or a reviewed ownership policy "
        "below. Every `Thread(target=...)` spawn in the scanned tree "
        "must map to one of these entries or the build fails.\n\n"
        "| Role | Entry points | Reachable functions |\n|---|---|---|\n")
    for role in sorted(hierarchy.THREAD_ROLES):
        entries = ", ".join(f"`{e}`"
                            for e in hierarchy.THREAD_ROLES[role])
        out.append(f"| `{role}` | {entries} "
                   f"| {len(contexts.get(role, {}))} |\n")

    out.append(
        "\n## Shared-state ownership\n\n"
        f"{len(locations)} shared locations are currently tracked "
        "across the roles above; every cross-thread-reachable location "
        "with an unlocked write must either share a lock (verified by "
        "the analyzer) or appear here with a reviewed policy — and the "
        "policy itself is machine-checked (a second writer on a "
        "`single-writer` entry, or a post-boot write on an "
        "`init-before-spawn` entry, fails the build; entries that stop "
        "matching anything are flagged as stale).\n\n"
        "| Location | Policy | Witness |\n|---|---|---|\n")
    for loc in sorted(hierarchy.OWNERSHIP):
        policy, witness = hierarchy.OWNERSHIP[loc]
        out.append(f"| `{loc}` | {policy} | {witness} |\n")
    out.append(_AMEND)
    return "".join(out)


def write(path=None) -> str:
    p = path or (REPO_ROOT / "docs" / "CONCURRENCY.md")
    text = render()
    p.write_text(text)
    return str(p)
