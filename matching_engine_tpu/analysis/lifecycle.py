"""Cross-language order-lifecycle equivalence checker.

The order-status state machine exists in FOUR independent
implementations, any of which can silently drift when a new status or
order type lands in only some of them:

- the proto enum (`OrderUpdate.Status`, matching_engine.proto) — the
  wire VOCABULARY and numeric values;
- the python engine layer (engine/oracle.py binds the names to the
  proto values; server/engine_runner.py applies status updates to live
  orders and rejects ops on terminal ones);
- the C++ lane engine (native/me_lanes.cpp: kNew..kRejected constants,
  the terminal guard, and the store_updates status writes);
- the online auditor (audit/auditor.py: the explicit `_LEGAL`
  transition table the shadow state machine enforces).

Each layer is reduced to the same machine shape and the four are proven
equal:

  vocabulary   {status name -> numeric value} (value None where a layer
               defers to the proto, e.g. the oracle's pb2 bindings)
  terminal     statuses from which no update may depart (the
               cancel/amend-on-dead guard in both engines, `_TERMINAL`
               in the auditor)
  relation     the (from -> to) update transitions. For the engines it
               is CONSTRUCTED from what the code can actually write to
               a live order: literal update statuses (CANCELED), the
               maker fill ternary (PARTIALLY_FILLED/FILLED), and
               status-PRESERVING updates (amend re-emits the current
               status => self-loops). For the auditor it is read
               directly off `_LEGAL`.

A status added to the proto but not the auditor, a terminal set that
differs between the C++ and python engines, or a new transition taught
to one layer only — each fails scripts/check.sh until all four agree.

Every extractor takes its source text/AST as an injectable parameter
(defaulting to the real tree) so the self-tests can prove each skew
class fires; an extractor that stops parsing its layer reports
lifecycle/extract-error rather than vacuous agreement.
"""

from __future__ import annotations

import ast
import dataclasses
import re

from matching_engine_tpu.analysis.common import (
    REPO_ROOT,
    Violation,
    load_sources,
)

_PROTO = REPO_ROOT / "matching_engine_tpu" / "proto" / "matching_engine.proto"
_ME_LANES = REPO_ROOT / "native" / "me_lanes.cpp"

_STATUS_NAMES = ("NEW", "PARTIALLY_FILLED", "FILLED", "CANCELED",
                 "REJECTED")


@dataclasses.dataclass
class Machine:
    layer: str
    vocab: dict[str, int | None]
    terminal: frozenset[str] | None          # None: layer doesn't define
    relation: frozenset[tuple[str, str]] | None
    errors: list[str] = dataclasses.field(default_factory=list)


def _relation_from_updates(vocab, terminal, targets,
                           preserving: bool) -> frozenset:
    """The machine an engine layer implies: from any live status, the
    statuses its update writes can produce, plus self-loops when a
    status-preserving update (amend) exists. Terminal statuses have no
    out-edges — the terminal guard rejects the op before the device
    sees it."""
    live = [s for s in vocab if s not in terminal]
    rel = {(s, t) for s in live for t in targets}
    if preserving:
        rel |= {(s, s) for s in live}
    return frozenset(rel)


# -- proto -------------------------------------------------------------------


def proto_machine(text: str | None = None) -> Machine:
    if text is None:
        text = _PROTO.read_text()
    m = Machine("proto", {}, None, None)
    em = re.search(r"enum\s+Status\s*\{([^}]*)\}", text)
    if em is None:
        m.errors.append("enum Status not found in matching_engine.proto")
        return m
    for name, val in re.findall(r"(\w+)\s*=\s*(\d+)\s*;", em.group(1)):
        m.vocab[name] = int(val)
    if not m.vocab:
        m.errors.append("enum Status parsed empty")
    return m


# -- auditor -----------------------------------------------------------------


def auditor_machine(tree: ast.Module | None = None) -> Machine:
    if tree is None:
        path = REPO_ROOT / "matching_engine_tpu" / "audit" / "auditor.py"
        tree = ast.parse(path.read_text())
    m = Machine("auditor", {}, None, None)
    legal: dict[str, tuple[str, ...]] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            # NEW, PARTIALLY_FILLED, ... = range(5)
            if isinstance(t, ast.Tuple) and isinstance(node.value, ast.Call) \
                    and isinstance(node.value.func, ast.Name) \
                    and node.value.func.id == "range":
                names = [e.id for e in t.elts if isinstance(e, ast.Name)]
                if len(names) == len(t.elts):
                    m.vocab = {n: i for i, n in enumerate(names)}
            elif isinstance(t, ast.Name) and t.id == "_TERMINAL" \
                    and isinstance(node.value, (ast.Tuple, ast.List)):
                m.terminal = frozenset(
                    e.id for e in node.value.elts
                    if isinstance(e, ast.Name))
            elif isinstance(t, ast.Name) and t.id == "_LEGAL" \
                    and isinstance(node.value, ast.Dict):
                for k, v in zip(node.value.keys, node.value.values):
                    if isinstance(k, ast.Name) \
                            and isinstance(v, (ast.Tuple, ast.List)):
                        legal[k.id] = tuple(
                            e.id for e in v.elts
                            if isinstance(e, ast.Name))
    if not m.vocab:
        m.errors.append("status tuple-assign from range() not found")
    if m.terminal is None:
        m.errors.append("_TERMINAL not found")
    if not legal:
        m.errors.append("_LEGAL not found")
    else:
        m.relation = frozenset(
            (src, dst) for src, dsts in legal.items() for dst in dsts)
    return m


# -- python engine (oracle vocabulary + engine_runner machine) ---------------


def _status_tuple(node: ast.expr) -> frozenset[str] | None:
    """A (FILLED, CANCELED, ...) literal tuple of status names."""
    if not isinstance(node, (ast.Tuple, ast.List)) or not node.elts:
        return None
    names = [e.id for e in node.elts if isinstance(e, ast.Name)
             and e.id in _STATUS_NAMES]
    if len(names) != len(node.elts):
        return None
    return frozenset(names)


def _sub_blocks(stmt) -> list[list]:
    out = []
    for field in ("body", "orelse", "finalbody"):
        b = getattr(stmt, field, None)
        if isinstance(b, list) and b and all(
                isinstance(x, ast.stmt) for x in b):
            out.append(b)
    for h in getattr(stmt, "handlers", None) or []:
        if h.body:
            out.append(h.body)
    return out


def _expr_walk(stmt):
    """The statement's own expressions — stops at nested statements
    (those belong to inner blocks and are scanned with their own
    cursor)."""
    stack = [c for c in ast.iter_child_nodes(stmt)
             if not isinstance(c, ast.stmt)]
    while stack:
        n = stack.pop()
        yield n
        stack.extend(c for c in ast.iter_child_nodes(n)
                     if not isinstance(c, ast.stmt))


def _block_resolves(append_stmt, parents, container,
                    path: str) -> frozenset[str] | None:
    """Scan the statement blocks enclosing `append_stmt` (innermost
    first, DIRECT statements only — a sibling branch's assignment must
    not leak in) for the latest `path.status = <literal | ternary>`
    before the append. None => no literal assignment dominates: the
    update PRESERVES the order's current status (the amend shape)."""
    cursor = append_stmt
    for block in parents.get(id(append_stmt), []):
        found = None   # ("lit", names) | ("nonlit",)
        for stmt in block:
            if stmt is cursor:
                break
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                t = stmt.targets[0]
                if isinstance(t, ast.Attribute) and t.attr == "status" \
                        and ast.unparse(t.value) == path:
                    v = stmt.value
                    if isinstance(v, ast.Name) and v.id in _STATUS_NAMES:
                        found = ("lit", frozenset({v.id}))
                    elif isinstance(v, ast.IfExp) \
                            and isinstance(v.body, ast.Name) \
                            and isinstance(v.orelse, ast.Name):
                        found = ("lit",
                                 frozenset({v.body.id, v.orelse.id}))
                    else:
                        found = ("nonlit",)
        if found is not None:
            return found[1] if found[0] == "lit" else None
        cursor = container.get(id(block))
        if cursor is None:
            break
    return None


def python_engine_machine(oracle_tree: ast.Module | None = None,
                          runner_tree: ast.Module | None = None) -> Machine:
    if oracle_tree is None:
        oracle_tree = ast.parse(
            (REPO_ROOT / "matching_engine_tpu" / "engine" /
             "oracle.py").read_text())
    if runner_tree is None:
        runner_tree = ast.parse(
            (REPO_ROOT / "matching_engine_tpu" / "server" /
             "engine_runner.py").read_text())
    m = Machine("python-engine", {}, None, None)

    # Vocabulary: oracle's NAME = pb2.OrderUpdate.Status.NAME bindings.
    for node in oracle_tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            src = ast.unparse(node.value)
            if src.startswith("pb2.OrderUpdate.Status."):
                bound = src.rsplit(".", 1)[-1]
                name = node.targets[0].id
                if name != bound:
                    m.errors.append(
                        f"oracle binds {name} to proto status {bound}")
                m.vocab[name] = None   # numeric value owned by the proto
    if not m.vocab:
        m.errors.append("oracle.py pb2 status bindings not found")

    # Terminal: `.status in (A, B, C)` guards whose branch REJECTS.
    guards: list[frozenset[str]] = []
    for node in ast.walk(runner_tree):
        if not isinstance(node, ast.If):
            continue
        for cmp_ in ast.walk(node.test):
            if not (isinstance(cmp_, ast.Compare)
                    and len(cmp_.ops) == 1
                    and isinstance(cmp_.ops[0], ast.In)
                    and isinstance(cmp_.left, ast.Attribute)
                    and cmp_.left.attr == "status"):
                continue
            names = _status_tuple(cmp_.comparators[0])
            if names is None:
                continue
            body_names = {n.id for b in node.body
                          for n in ast.walk(b) if isinstance(n, ast.Name)}
            if "REJECTED" in body_names:
                guards.append(names)
    if not guards:
        m.errors.append("engine_runner terminal guard not found")
    elif len(set(guards)) > 1:
        m.errors.append(
            f"engine_runner terminal guards disagree: "
            f"{sorted(set(map(tuple, map(sorted, guards))))}")
    else:
        m.terminal = guards[0]

    # Update writes: storage_updates.append((oid, STATUS, ...)).
    # Index every statement's enclosing-block chain so the status
    # element of an update row resolves against the assignments that
    # DOMINATE it (same block or an enclosing one), never a sibling
    # branch's.
    parents: dict[int, list] = {}     # id(stmt) -> [block, ...] inner-first
    container: dict[int, ast.stmt] = {}   # id(block) -> containing stmt

    def index_stmt(stmt, chain):
        parents[id(stmt)] = chain
        for block in _sub_blocks(stmt):
            container[id(block)] = stmt
            for s in block:
                index_stmt(s, [block] + chain)

    for fn in ast.walk(runner_tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for s in fn.body:
                index_stmt(s, [fn.body])

    targets: set[str] = set()
    preserving = False
    for fn in ast.walk(runner_tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for stmt in ast.walk(fn):
            if not isinstance(stmt, ast.stmt) or id(stmt) not in parents:
                continue
            for call in _expr_walk(stmt):
                if not (isinstance(call, ast.Call)
                        and isinstance(call.func, ast.Attribute)
                        and call.func.attr == "append"
                        and isinstance(call.func.value, ast.Attribute)
                        and call.func.value.attr == "storage_updates"
                        and call.args
                        and isinstance(call.args[0], ast.Tuple)
                        and len(call.args[0].elts) >= 2):
                    continue
                el = call.args[0].elts[1]
                if isinstance(el, ast.Name) and el.id in _STATUS_NAMES:
                    targets.add(el.id)
                elif isinstance(el, ast.Attribute) and el.attr == "status":
                    path = ast.unparse(el.value)
                    res = _block_resolves(stmt, parents, container, path)
                    if res is None:
                        preserving = True
                    else:
                        targets |= res
    if not targets:
        m.errors.append("engine_runner storage_updates writes not found")
    if m.vocab and m.terminal is not None and targets:
        m.relation = _relation_from_updates(
            m.vocab, m.terminal, targets, preserving)
    return m


# -- C++ lane engine ---------------------------------------------------------


_CAMEL = re.compile(r"(?<!^)(?=[A-Z])")


def _k_name(k: str) -> str:
    """kPartiallyFilled -> PARTIALLY_FILLED."""
    return _CAMEL.sub("_", k[1:]).upper()


def cpp_machine(text: str | None = None) -> Machine:
    if text is None:
        text = _ME_LANES.read_text()
    m = Machine("me_lanes.cpp", {}, None, None)
    text = re.sub(r"//[^\n]*", "", text)

    cm = re.search(
        r"constexpr\s+int\s+(kNew\s*=[^;]*);", text)
    if cm is None:
        m.errors.append("status constexpr block (kNew = ...) not found")
    else:
        for name, val in re.findall(r"(k\w+)\s*=\s*(\d+)", cm.group(1)):
            m.vocab[_k_name(name)] = int(val)

    # Terminal: every `x.status == kA || x.status == kB || x.status == kC`
    # chain over an ORDER OBJECT member must name the same set. The
    # member access ([.>]status) is the discriminator: a bare local
    # `status == kNew || ...` tests the device RESULT of this op, not
    # which states reject further ops.
    chains = re.findall(
        r"[.>]status\s*==\s*(k\w+)\s*\|\|\s*[\w>\-.]*[.>]status\s*==\s*"
        r"(k\w+)\s*\|\|\s*[\w>\-.]*[.>]status\s*==\s*(k\w+)", text)
    sets = {frozenset(_k_name(k) for k in c) for c in chains}
    if not chains:
        m.errors.append("terminal status guard chain not found")
    elif len(sets) > 1:
        m.errors.append(f"terminal guard chains disagree: {sorted(map(sorted, sets))}")
    else:
        m.terminal = next(iter(sets))

    # Update-status writes into the store_updates buffer.
    writes = re.findall(
        r"put_u8\(&ctx\.store_updates,\s*static_cast<uint8_t>\(([^()]+)\)\)",
        text)
    targets: set[str] = set()
    preserving = False
    ternaries = dict(
        (var, frozenset({_k_name(a), _k_name(b)}))
        for var, a, b in re.findall(
            r"(\w+)\.status\s*=\s*[^;?]*\?\s*(k\w+)\s*:\s*(k\w+)\s*;", text))
    for expr in writes:
        expr = expr.strip()
        if expr.startswith("k"):
            targets.add(_k_name(expr))
        elif expr.endswith(".status"):
            var = expr[:-len(".status")].rsplit(".", 1)[-1]
            if var in ternaries:
                targets |= ternaries[var]
            else:
                preserving = True
    if not writes:
        m.errors.append("store_updates status writes not found")
    if m.vocab and m.terminal is not None and targets:
        m.relation = _relation_from_updates(
            m.vocab, m.terminal, targets, preserving)
    return m


# -- the equivalence check ---------------------------------------------------


def compare(machines: list[Machine]) -> list[Violation]:
    vs: list[Violation] = []
    for m in machines:
        for err in m.errors:
            vs.append(Violation(
                "lifecycle/extract-error", m.layer, err))

    ok = [m for m in machines if not m.errors]
    if len(ok) < 2:
        return vs

    names = {m.layer: set(m.vocab) for m in ok if m.vocab}
    base_layer = ok[0].layer
    base = names.get(base_layer, set())
    for layer, n in names.items():
        if n != base:
            only_a = sorted(base - n)
            only_b = sorted(n - base)
            vs.append(Violation(
                "lifecycle/vocabulary-skew", layer,
                f"status vocabulary differs from {base_layer}: "
                f"missing {only_a or '[]'}, extra {only_b or '[]'}"))

    # Numeric values: any two layers that both pin a value must agree.
    for name in sorted(base):
        vals = {m.layer: m.vocab[name] for m in ok
                if m.vocab.get(name) is not None}
        if len(set(vals.values())) > 1:
            vs.append(Violation(
                "lifecycle/value-skew", name,
                f"numeric value differs across layers: {vals}"))

    terms = {m.layer: m.terminal for m in ok if m.terminal is not None}
    tvals = set(terms.values())
    if len(tvals) > 1:
        vs.append(Violation(
            "lifecycle/terminal-skew", "+".join(sorted(terms)),
            f"terminal sets differ: "
            f"{ {k: sorted(v) for k, v in sorted(terms.items())} }"))

    rels = {m.layer: m.relation for m in ok if m.relation is not None}
    if len(set(rels.values())) > 1:
        layers = sorted(rels)
        ref = rels[layers[0]]
        for layer in layers[1:]:
            if rels[layer] != ref:
                missing = sorted(ref - rels[layer])
                extra = sorted(rels[layer] - ref)
                vs.append(Violation(
                    "lifecycle/transition-skew", layer,
                    f"update transitions differ from {layers[0]}: "
                    f"missing {missing or '[]'}, extra {extra or '[]'}"))
    return vs


def machines() -> list[Machine]:
    # load_sources keeps the parse cache warm for the other analyzers.
    load_sources(("audit",))
    return [proto_machine(), auditor_machine(), python_engine_machine(),
            cpp_machine()]


def run() -> list[Violation]:
    return compare(machines())
