"""Repo-native static-analysis suite — the guardrails for the
invariants that exist only as prose and runtime fuzz everywhere else:

- lockorder:  lock acquisition graph vs the declared hierarchy
              (hub→auditor ordering, nothing slow under the hub lock,
              with-scoped locking) — analysis/hierarchy.py is the
              declaration, docs/CONCURRENCY.md the rendered contract;
- lockset:    Eraser-style race detection — shared state reachable from
              two thread roles (hierarchy.THREAD_ROLES) must share a
              lock or carry a reviewed OWNERSHIP policy (single-writer,
              init-before-spawn, gil-atomic, instance-confined);
- determinism: taint from nondeterminism sources (wall clock, random,
              id(), thread ids, unordered iteration) to the replay
              surfaces (store rows, feed/drop-copy payloads, seq
              stamps, checkpoints) — the HA replica's bit-identity
              contract, with declared wall-clock fields allowlisted;
- lifecycle:  the order-status machine extracted from its FOUR
              implementations (proto enum, python engine, me_lanes.cpp,
              auditor `_LEGAL`) and proven equal;
- jitpurity:  jax.jit purity (no host-impure calls in traced code),
              donation discipline (no double-donated / aliased
              buffers), and the utils/jax_compat routing convention;
- abi:        byte-for-byte MeOpRec/MeGwOp/MeOp layout agreement
              between the C headers and the python mirrors, proven
              without building the .so;
- doccheck:   metric/flag ⇄ docs/OPERATIONS.md coherence, both
              directions.

Run as tier-1 tests (tests/test_analysis.py), as one gate
(scripts/check.sh), or directly:

    python -m matching_engine_tpu.analysis run [--json]
    python -m matching_engine_tpu.analysis render-concurrency
"""

from __future__ import annotations

from matching_engine_tpu.analysis.common import Violation  # noqa: F401


def run_all() -> dict[str, list[Violation]]:
    """All seven analyzers, keyed by name. Import inside so `import
    matching_engine_tpu.analysis` stays cheap for tooling."""
    from matching_engine_tpu.analysis import (
        abi,
        determinism,
        doccheck,
        jitpurity,
        lifecycle,
        lockorder,
        lockset,
    )

    return {
        "lock-order": lockorder.run(),
        "lockset": lockset.run(),
        "determinism": determinism.run(),
        "lifecycle": lifecycle.run(),
        "jit-purity": jitpurity.run(),
        "abi": abi.run(),
        "doc-coherence": doccheck.run(),
    }
