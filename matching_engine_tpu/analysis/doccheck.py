"""Metric/flag ⇄ docs coherence linter.

Generalizes the tier-1 doc-lint (tests/test_obs.py checks doc→code for
the metric table) to BOTH directions and to server flags:

- every metric the package emits under a literal name must have a row
  in docs/OPERATIONS.md's Observability table, and every documented row
  must be emitted (registry names; the exporter adds `me_`/`_total`);
- every `--flag` the server registers (server/main.py) must be
  mentioned in docs/OPERATIONS.md, and every `--flag` token
  OPERATIONS.md mentions must exist in some shipped entry point
  (server, CLI client, benches, scripts/*.sh).

Names that only materialize dynamically (f-strings, per-lane series,
"+ kind" suffixes) are out of scope here — the pre-registration
convention (register the literal zero first, PR 8) is what makes the
static table complete, and this linter is the tool that keeps that
convention honest.
"""

from __future__ import annotations

import ast
import re

from matching_engine_tpu.analysis.common import (
    PKG_ROOT,
    REPO_ROOT,
    Violation,
    call_name,
    load_sources,
    site,
)

OPERATIONS = REPO_ROOT / "docs" / "OPERATIONS.md"

# Emit-call shapes -> the doc row type their names belong to.
_EMITS = {"inc": "counter", "set_gauge": "gauge", "observe": "histogram"}

# Metrics that are deliberately undocumented: NONE. Keep this empty —
# document the metric instead (the whole point of the linter).
ALLOW_UNDOCUMENTED: frozenset[str] = frozenset()


def _doc_rows(doc: str) -> list[tuple[str, str]]:
    return re.findall(
        r"^\|\s*`([a-z0-9_]+)`\s*\|\s*(counter|gauge|ema|histogram)\s*\|",
        doc, re.M)


def collect_emitted(sources) -> dict[str, tuple[str, str]]:
    """Literal metric name -> (doc row type, site)."""
    out: dict[str, tuple[str, str]] = {}
    for src in sources:
        for n in ast.walk(src.tree):
            if not isinstance(n, ast.Call):
                continue
            name = call_name(n)
            lit = None
            typ = None
            if name in _EMITS and n.args \
                    and isinstance(n.args[0], ast.Constant) \
                    and isinstance(n.args[0].value, str):
                lit, typ = n.args[0].value, _EMITS[name]
            elif name == "ema_gauge" and n.args \
                    and isinstance(n.args[0], ast.Constant) \
                    and isinstance(n.args[0].value, str):
                lit, typ = n.args[0].value + "_ema", "ema"
            elif name == "Timer" and len(n.args) >= 2 \
                    and isinstance(n.args[1], ast.Constant) \
                    and isinstance(n.args[1].value, str):
                lit, typ = n.args[1].value, "histogram"
            if lit and re.fullmatch(r"[a-z0-9_]+", lit):
                out.setdefault(lit, (typ, site(src, n)))
    return out


def check_metrics(doc: str | None = None,
                  sources=None) -> list[Violation]:
    """`doc`/`sources` injectable for the self-tests; defaults to the
    real OPERATIONS.md and the whole package."""
    vs: list[Violation] = []
    if doc is None:
        doc = OPERATIONS.read_text()
        min_rows = 40
    else:
        min_rows = 1
    rows = dict(_doc_rows(doc))
    if len(rows) < min_rows:
        return [Violation("doc-coherence/metric-table", str(OPERATIONS),
                          "Observability metric table missing or shrunk")]
    if sources is None:
        sources = load_sources([""], root=PKG_ROOT)
    emitted = collect_emitted(sources)

    # Histogram rows document the base name; Timer/observe emit it too,
    # and ema rows ride the _ema suffix (collect_emitted normalizes).
    for name, (typ, where) in sorted(emitted.items()):
        if name in ALLOW_UNDOCUMENTED:
            continue
        if name not in rows:
            vs.append(Violation(
                "doc-coherence/undocumented-metric", where,
                f"metric '{name}' ({typ}) is emitted but has no row in "
                f"docs/OPERATIONS.md's Observability table"))
        elif rows[name] != typ:
            vs.append(Violation(
                "doc-coherence/metric-type", where,
                f"metric '{name}' emitted as {typ} but documented as "
                f"{rows[name]}"))

    # Reverse direction: the proven regex surface from the tier-1 lint
    # (emit literals + native aux tuples + stage constants).
    src_text = "\n".join(s.text for s in sources)

    def doc_name_emitted(name: str, typ: str) -> bool:
        if typ == "counter":
            pats = [rf'inc\(\s*"{name}"', rf'"{name}"\)']
        elif typ == "gauge":
            pats = [rf'set_gauge\(\s*"{name}"']
        elif typ == "ema":
            base = name[:-len("_ema")] if name.endswith("_ema") else name
            pats = [rf'ema_gauge\(\s*"{base}"', rf'Timer\([^)]*"{base}"']
        else:
            pats = [rf'observe\(\s*"{name}"', rf'Timer\([^)]*"{name}"',
                    rf'STAGE_[A-Z_]+ = "{name}"']
        return any(re.search(p, src_text, re.S) for p in pats)

    for name, typ in sorted(rows.items()):
        if not doc_name_emitted(name, typ):
            vs.append(Violation(
                "doc-coherence/orphan-metric-row", f"docs/OPERATIONS.md",
                f"documented metric '{name}' ({typ}) is never emitted"))
    return vs


def collect_flags(sources) -> dict[str, str]:
    """--flag -> site, from add_argument literals."""
    out: dict[str, str] = {}
    for src in sources:
        for n in ast.walk(src.tree):
            if isinstance(n, ast.Call) \
                    and call_name(n) == "add_argument":
                for a in n.args:
                    if isinstance(a, ast.Constant) \
                            and isinstance(a.value, str) \
                            and a.value.startswith("--"):
                        out.setdefault(a.value, site(src, n))
    return out


def check_flags(doc: str | None = None) -> list[Violation]:
    vs: list[Violation] = []
    if doc is None:
        doc = OPERATIONS.read_text()
    server_flags = collect_flags(load_sources(["server/main.py"]))
    for flag, where in sorted(server_flags.items()):
        # Word-boundary match: '--trace' must not ride on the
        # documented '--trace-dir' (substring containment would let
        # any prefix-of-a-documented-flag pass undetected).
        if not re.search(re.escape(flag) + r"(?![a-z0-9-])", doc):
            vs.append(Violation(
                "doc-coherence/undocumented-flag", where,
                f"server flag '{flag}' is not mentioned anywhere in "
                f"docs/OPERATIONS.md"))

    # Reverse: every --token the doc mentions must exist somewhere.
    known = dict(server_flags)
    known.update(collect_flags(load_sources(
        ["client", "benchmarks"], root=PKG_ROOT.parent) +
        load_sources(["client"])))
    for sh in sorted((REPO_ROOT / "scripts").glob("*.sh")):
        for tok in re.findall(r"--[a-z][a-z0-9-]*", sh.read_text()):
            known.setdefault(tok, str(sh))
    for tok in sorted(set(re.findall(r"`(--[a-z][a-z0-9-]*)", doc))):
        if tok not in known:
            vs.append(Violation(
                "doc-coherence/orphan-flag", "docs/OPERATIONS.md",
                f"documented flag '{tok}' is registered by no entry "
                f"point (server/CLI/bench/scripts)"))
    return vs


def run() -> list[Violation]:
    return check_metrics() + check_flags()
