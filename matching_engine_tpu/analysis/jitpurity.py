"""jit-purity / donation analyzer.

Walks every `jax.jit`-rooted function in engine/, parallel/ and sim/
(decorated defs, `x = jax.jit(f, ...)` bindings, and jit-of-shard_map
compositions) plus everything they transitively call in those modules,
and enforces the rules the donated-book kernels live by:

- purity: no host-impure calls (time/random/IO/print) inside traced
  code — at trace time they freeze one ambient value into the compiled
  artifact, the classic silent-wrong-kernel bug;
- donation: a jitted callable with `donate_argnums` must never be
  passed the same buffer expression in two positions (XLA would alias a
  donated input), and construction of the donated pytrees (BookBatch)
  must not feed one array object to two fields — `engine/book.py`'s
  init_book comment is this rule in prose;
- version-compat: `jax.experimental.shard_map` / `check_rep=` must not
  be used directly anywhere in the package — every mesh call routes
  through utils/jax_compat (the PR 4 triage convention), which owns the
  0.4.x/0.5.x spelling skew.
"""

from __future__ import annotations

import ast

from matching_engine_tpu.analysis.common import (
    PKG_ROOT,
    Source,
    Violation,
    call_name,
    dotted,
    load_sources,
    site,
)

JIT_SCAN_DIRS = ("engine", "parallel", "sim", "gym")

# Pytrees whose construction feeds donated buffers: duplicate argument
# expressions alias what donation will invalidate.
DONATED_PYTREES = frozenset({"BookBatch"})

# Host-impure call prefixes (first dotted segment / first two segments).
_IMPURE_HEADS = frozenset({"time", "random", "datetime", "os", "uuid",
                           "secrets", "socket"})
_IMPURE_PAIRS = frozenset({"np.random", "numpy.random"})
_IMPURE_BARE = frozenset({"open", "print", "input"})

_COMPAT_MODULE = "jax_compat"


def _is_impure_call(node: ast.Call) -> str | None:
    d = dotted(node.func)
    if d is None:
        return None
    head = d.split(".", 1)[0]
    pair = ".".join(d.split(".")[:2])
    if d in _IMPURE_BARE:
        return d
    if pair in _IMPURE_PAIRS:
        return d
    if head in _IMPURE_HEADS and "." in d:
        return d
    return None


def _int_tuple(node: ast.expr) -> tuple[int, ...]:
    """Literal donate_argnums/static_argnums value -> positions."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
        return tuple(out)
    return ()


class _JitRoots(ast.NodeVisitor):
    """Find jit roots + jitted-callable donation signatures in one
    module."""

    def __init__(self, src: Source):
        self.src = src
        self.roots: list[tuple[str, str]] = []       # (func name, site)
        self.jitted: dict[str, tuple[int, ...]] = {}  # callable -> donated
        self.assigns: dict[str, ast.expr] = {}        # local name -> value

    def _jit_call(self, node: ast.expr) -> ast.Call | None:
        """The jax.jit(...) call inside a decorator/assign value, if
        any: jax.jit(f, ...) or partial(jax.jit, ...)."""
        if not isinstance(node, ast.Call):
            return None
        d = dotted(node.func)
        if d in ("jax.jit", "jit"):
            return node
        if d in ("partial", "functools.partial") and node.args:
            inner = dotted(node.args[0])
            if inner in ("jax.jit", "jit"):
                return node
        return None

    def _donated(self, call: ast.Call) -> tuple[int, ...]:
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                return _int_tuple(kw.value)
        return ()

    def _resolve_fn_name(self, node: ast.expr) -> str | None:
        """jax.jit's first argument -> the module-level def it traces:
        a bare Name, possibly through a local `mapped = shard_map(fn,
        ...)` binding."""
        if isinstance(node, ast.Name):
            v = self.assigns.get(node.id)
            if v is None:
                return node.id
            return self._resolve_fn_name(v)
        if isinstance(node, ast.Call):
            d = dotted(node.func)
            if d and d.rsplit(".", 1)[-1] in ("shard_map", "vmap", "pmap"):
                return self._resolve_fn_name(node.args[0]) \
                    if node.args else None
        return None

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            if isinstance(t, ast.Name):
                self.assigns[t.id] = node.value
        call = self._jit_call(node.value)
        if call is not None and call.args:
            fn = self._resolve_fn_name(call.args[0])
            if fn is not None:
                self.roots.append((fn, site(self.src, node)))
            for t in node.targets:
                name = t.id if isinstance(t, ast.Name) else (
                    t.attr if isinstance(t, ast.Attribute) else None)
                if name:
                    self.jitted[name] = self._donated(call)
        self.generic_visit(node)

    def _visit_def(self, node) -> None:
        for dec in node.decorator_list:
            d = dotted(dec)
            if d in ("jax.jit", "jit"):
                self.roots.append((node.name, site(self.src, node)))
                self.jitted[node.name] = ()
            call = self._jit_call(dec)
            if call is not None:
                self.roots.append((node.name, site(self.src, node)))
                self.jitted[node.name] = self._donated(call)
        self.generic_visit(node)

    visit_FunctionDef = _visit_def
    visit_AsyncFunctionDef = _visit_def


def _module_functions(src: Source) -> dict[str, ast.AST]:
    out: dict[str, ast.AST] = {}
    for n in src.tree.body:
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[n.name] = n
    return out


def _imports(src: Source) -> dict[str, tuple[str, str]]:
    out: dict[str, tuple[str, str]] = {}
    for n in ast.walk(src.tree):
        if isinstance(n, ast.ImportFrom) and n.module:
            for a in n.names:
                out[a.asname or a.name] = (n.module, a.name)
    return out


def check_traced_purity(sources: list[Source]) -> list[Violation]:
    """Rule jit-purity/impure-call over the traced closure."""
    vs: list[Violation] = []
    fns: dict[str, tuple[Source, ast.AST]] = {}
    imports: dict[str, dict[str, tuple[str, str]]] = {}
    roots: list[tuple[str, str, str]] = []   # (mod, fn, site)
    for src in sources:
        mod = src.modname
        for name, node in _module_functions(src).items():
            fns[f"{mod}.{name}"] = (src, node)
        imports[mod] = _imports(src)
        jr = _JitRoots(src)
        jr.visit(src.tree)
        for fn, w in jr.roots:
            roots.append((mod, fn, w))

    # Transitive closure of traced functions, name-resolved through
    # module locals and package imports.
    traced: dict[str, str] = {}   # qual -> root site that pulled it in
    stack = []
    for mod, fn, w in roots:
        qual = f"{mod}.{fn}"
        if qual in fns and qual not in traced:
            traced[qual] = w
            stack.append(qual)
    while stack:
        qual = stack.pop()
        src, node = fns[qual]
        mod = qual.rsplit(".", 1)[0]
        for n in ast.walk(node):
            if not isinstance(n, ast.Call):
                continue
            name = call_name(n)
            if name is None:
                continue
            callee = f"{mod}.{name}"
            if callee not in fns:
                bound = imports.get(mod, {}).get(name)
                callee = f"{bound[0]}.{bound[1]}" if bound else ""
            if callee in fns and callee not in traced:
                traced[callee] = traced[qual]
                stack.append(callee)

    for qual in sorted(traced):
        src, node = fns[qual]
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                imp = _is_impure_call(n)
                if imp is not None:
                    vs.append(Violation(
                        "jit-purity/impure-call", site(src, n),
                        f"host-impure call {imp}() inside jit-traced "
                        f"{qual} (traced via {traced[qual]}) — the value "
                        f"freezes at trace time"))
    return vs


def check_donation(sources: list[Source],
                   call_sources: list[Source]) -> list[Violation]:
    """Rules jit-purity/double-donation and /aliased-pytree."""
    vs: list[Violation] = []
    jitted: dict[str, tuple[int, ...]] = {}
    for src in sources:
        jr = _JitRoots(src)
        jr.visit(src.tree)
        for name, don in jr.jitted.items():
            if don:
                jitted[name] = don

    def norm(e: ast.expr) -> str | None:
        """Comparable form for alias detection: only simple names /
        attribute chains (two calls like z() are distinct buffers)."""
        return dotted(e)

    def is_buffer_dup(r: str, assigns: dict[str, ast.expr]) -> bool:
        """A duplicated expression aliases donated *buffers* only if it
        can hold an array. A bare name locally bound to a non-array
        constructor (PartitionSpec etc.) is shared metadata, not a
        buffer — parallel/sharding.py's spec pytrees are built that
        way on purpose."""
        if "." in r:
            return True                    # book.next_seq-style chains
        binding = assigns.get(r)
        if isinstance(binding, ast.Call):
            d = dotted(binding.func) or ""
            return d.split(".", 1)[0] in ("jnp", "np", "jax", "jaxlib")
        return True                        # parameter/outer: assume buffer

    for src in call_sources:
        scopes: list[tuple[ast.AST, dict[str, ast.expr]]] = []
        for fn in ast.walk(src.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Module)):
                assigns = {}
                for a in ast.walk(fn):
                    if isinstance(a, ast.Assign):
                        for t in a.targets:
                            if isinstance(t, ast.Name):
                                assigns[t.id] = a.value
                scopes.append((fn, assigns))
        scope_of: dict[ast.AST, dict[str, ast.expr]] = {}
        for fn, assigns in scopes:
            for n in ast.walk(fn):
                scope_of[n] = assigns       # innermost wins (walk order)
        for n in ast.walk(src.tree):
            if not isinstance(n, ast.Call):
                continue
            name = call_name(n)
            don = jitted.get(name or "")
            if don:
                rendered = [norm(a) for a in n.args]
                for pos in don:
                    if pos >= len(rendered) or rendered[pos] is None:
                        continue
                    for j, other in enumerate(rendered):
                        if j != pos and other == rendered[pos]:
                            vs.append(Violation(
                                "jit-purity/double-donation",
                                site(src, n),
                                f"{name}() receives `{other}` at donated "
                                f"position {pos} and again at position "
                                f"{j} — a donated buffer may not alias "
                                f"another argument"))
            if name in DONATED_PYTREES:
                seen: dict[str, str] = {}
                fields = [(f"arg{j}", a) for j, a in enumerate(n.args)]
                fields += [(kw.arg or "**", kw.value) for kw in n.keywords]
                for fname, expr in fields:
                    r = norm(expr)
                    if r is None:
                        continue
                    if r in seen and is_buffer_dup(r, scope_of.get(n, {})):
                        vs.append(Violation(
                            "jit-purity/aliased-pytree", site(src, n),
                            f"{name}(...) feeds `{r}` to both "
                            f"'{seen[r]}' and '{fname}' — donated "
                            f"pytree fields must be distinct buffers "
                            f"(engine/book.py init_book rule)"))
                    else:
                        seen[r] = fname
    return vs


def check_compat_routing(pkg_sources: list[Source]) -> list[Violation]:
    """Rule jit-purity/compat-bypass: direct jax.experimental.shard_map
    or check_rep spelling outside utils/jax_compat.py."""
    vs: list[Violation] = []
    for src in pkg_sources:
        if src.path.stem == _COMPAT_MODULE:
            continue
        if src.path.parts[-2:][0] == "analysis":
            continue   # this package names the symbols in its rules
        for n in ast.walk(src.tree):
            if isinstance(n, ast.ImportFrom) and n.module and \
                    n.module.startswith("jax.experimental"):
                names = {a.name for a in n.names}
                if "shard_map" in names or \
                        n.module.endswith("shard_map"):
                    vs.append(Violation(
                        "jit-purity/compat-bypass", site(src, n),
                        "direct jax.experimental.shard_map import — "
                        "route through utils/jax_compat.shard_map "
                        "(owns the 0.4.x/0.5.x spelling skew)"))
            elif isinstance(n, ast.Attribute):
                d = dotted(n)
                if d in ("jax.experimental.shard_map.shard_map",
                         "jax.experimental.shard_map"):
                    vs.append(Violation(
                        "jit-purity/compat-bypass", site(src, n),
                        f"direct {d} use — route through "
                        f"utils/jax_compat.shard_map"))
            elif isinstance(n, ast.Call):
                for kw in n.keywords:
                    if kw.arg == "check_rep":
                        vs.append(Violation(
                            "jit-purity/compat-bypass", site(src, n),
                            "check_rep= is the pre-0.5 spelling — pass "
                            "check_vma= through utils/jax_compat"))
    return vs


def run() -> list[Violation]:
    jit_sources = load_sources(JIT_SCAN_DIRS)
    pkg_sources = load_sources([""], root=PKG_ROOT)
    vs = check_traced_purity(jit_sources)
    vs += check_donation(jit_sources, pkg_sources)
    vs += check_compat_routing(pkg_sources)
    return vs
