"""Q4 fixed-point price arithmetic.

Semantics preserved exactly from the reference's normalizer
(/root/reference/include/domain/price.hpp:6-29):

- Prices are scaled integers; the engine's canonical scale is 4 decimal
  places ("Q4"): price_q4 = real_price * 10^4.
- `normalize_to_q4(price, scale)` rescales a price quoted with `scale`
  decimal places (0..18) to Q4.
    * upscale (scale < 4): multiply by 10^(4-scale); int64 overflow raises.
    * downscale (scale > 4): divide by 10^(scale-4), truncating toward zero
      (so 10050 at scale 9 normalizes to 0).
    * scale outside [0, 18] raises.

Host math is exact arbitrary-precision Python int checked against int64
bounds, mirroring the C++ overflow checks at price.hpp:23-24.

Device-side note: the TPU engine stores book prices as int32 Q4 lanes (the
MXU/VPU-native integer width; int64 lowers to emulated pairs on TPU). That
bounds on-device prices to Q4 <= 2**31-1, i.e. 214,748.3647 per unit.
Orders normalizing above that are rejected at validation with an overflow
error — same failure mode as the reference's int64 ceiling, at the device
lane width. `normalize_to_q4_jax` is the pure-array mirror used by on-device
order-flow generators (sim/) and tests.
"""

from __future__ import annotations

import jax.numpy as jnp

K_TARGET_SCALE = 4
INT64_MAX = 2**63 - 1
INT64_MIN = -(2**63)
MAX_DEVICE_PRICE_Q4 = 2**31 - 1

# 10^0 .. 10^18 (largest power of ten representable in int64).
POW10 = tuple(10**i for i in range(19))


class PriceError(ValueError):
    """Raised for out-of-range scales or int64 overflow during rescale."""


def normalize_to_q4(price: int, raw_scale: int) -> int:
    """Rescale `price` quoted with `raw_scale` decimals to the Q4 grid."""
    if not 0 <= raw_scale <= 18:
        raise PriceError(f"scale {raw_scale} out of range [0, 18]")
    if not INT64_MIN <= price <= INT64_MAX:
        raise PriceError(f"price {price} outside int64 range")
    if raw_scale == K_TARGET_SCALE:
        return price
    if raw_scale < K_TARGET_SCALE:
        scaled = price * POW10[K_TARGET_SCALE - raw_scale]
        if not INT64_MIN <= scaled <= INT64_MAX:
            raise PriceError(
                f"price {price} at scale {raw_scale} overflows int64 when "
                f"normalized to Q4"
            )
        return scaled
    # Downscale: truncate toward zero (Python // floors, so divide magnitudes).
    div = POW10[raw_scale - K_TARGET_SCALE]
    q = abs(price) // div
    return -q if price < 0 else q


def normalize_to_q4_jax(price, raw_scale):
    """Array mirror of `normalize_to_q4` for on-device flow generation.

    Returns (price_q4, ok); ok=False marks out-of-range scales AND rescales
    whose result would not fit the lane dtype (no exceptions under jit —
    where the host path raises PriceError, this flags). Truncation toward
    zero matches the host path wherever ok=True.

    Lane-width care (the default lane is int32 with jax x64 disabled):
    - Upscale shift is at most 4 (raw_scale >= 0), so the multiplier is at
      most 10^4 and fits any lane; only the *product* can overflow, which is
      detected with a bound check before multiplying.
    - Downscale shift reaches 14 (scale 18), where 10^shift wraps int32 —
      so the divide runs in two exact steps of at most 10^9 each
      (trunc(trunc(x/a)/b) == trunc(x/(a*b)) for non-negative x).
    """
    price = jnp.asarray(price)
    raw_scale = jnp.asarray(raw_scale, dtype=jnp.int32)
    ok = (raw_scale >= 0) & (raw_scale <= 18)
    shift = raw_scale - K_TARGET_SCALE
    dt = price.dtype
    ten = jnp.asarray(10, dtype=dt)
    lane_max = jnp.asarray(jnp.iinfo(dt).max, dtype=dt)

    # Upscale: shift in [-4, 0) => multiplier 10^k, k <= 4.
    up_k = jnp.clip(-shift, 0, K_TARGET_SCALE)
    up_mag = ten ** up_k
    up_fits = jnp.abs(price) <= lane_max // up_mag
    up = price * up_mag

    # Downscale: shift in (0, 14]; split 10^shift = 10^a * 10^b, a,b <= 9.
    down_shift = jnp.clip(shift, 0, 14)
    a = jnp.minimum(down_shift, 9)
    b = down_shift - a
    down = jnp.abs(price) // (ten ** a) // (ten ** b)
    down = jnp.sign(price) * down

    out = jnp.where(shift == 0, price, jnp.where(shift < 0, up, down))
    ok = ok & jnp.where(shift < 0, up_fits, True)
    return jnp.where(ok, out, 0), ok
