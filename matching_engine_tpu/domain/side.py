"""Side enum pinning.

`Side` aliases the proto enum, and the module import asserts BUY=1/SELL=2 so
that the storage layer's CHECK constraints and the device-side integer
encodings break loudly if the proto is ever renumbered — the same guard the
reference expresses with static_asserts (include/domain/side.hpp:5-9).
"""

from matching_engine_tpu.proto import pb2

Side = pb2.Side
BUY = pb2.BUY
SELL = pb2.SELL

assert BUY == 1, "proto Side.BUY must stay 1 (storage CHECKs and device encoding rely on it)"
assert SELL == 2, "proto Side.SELL must stay 2 (storage CHECKs and device encoding rely on it)"
