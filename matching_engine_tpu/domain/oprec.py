"""The flat binary op-record codec — ONE wire format for every edge.

Every ingress path that carries orders in bulk (the SubmitOrderBatch RPC,
recorded-flow replay in the benches, the CLI's submit-batch verb, and any
future shared-memory edge) is a codec over the same fixed-width
little-endian record. The record is the *engine-facing* op tuple: the
collapsed (order_type, tif) device code and the Q4-normalized price — what
MeGwOp (native/me_gwop.h) carries across the ring — so decoding a batch
never re-runs price normalization or tif collapsing per op, and the C++
lane engine converts a packed payload straight into ring records in one
crossing (me_oprec_to_gwop).

Layout (little-endian, 384 bytes/record, natural C alignment — mirrored
byte-for-byte by MeOpRec in native/me_gwop.h; tests fuzz the round trip
python <-> C++):

    offset  field          type
    0       op             u8   1=submit / 2=cancel / 3=amend (MeGwOp.op)
    1       side           u8   BUY=1 / SELL=2 (submits)
    2       otype          u8   collapsed device code (proto.collapse_otype)
    3       flags          u8   reserved, must be 0
    4       price_q4       i32  normalized; 0 for MARKET
    8       quantity       i64  submit qty / amend new-quantity
    16      symbol_len     u16
    18      client_id_len  u16
    20      order_id_len   u16
    22      writer         u16  shm multi-producer lane id (0 elsewhere)
    24      symbol         64 bytes
    88      client_id      256 bytes
    344     order_id       36 bytes ("OID-<n>" cancel/amend target)
    380     (pad)          4 bytes

A batch payload (and a recorded op FILE) is the 8-byte magic ``MEOPREC1``
followed by N records. Encode/decode are numpy-vectorized: the hot cost is
one structured-array copy, never per-op python.
"""

from __future__ import annotations

import numpy as np

MAGIC = b"MEOPREC1"
RECORD_SIZE = 384
HEADER_SIZE = len(MAGIC)

# Wire op codes — identical to MeGwOp.op (native/me_gwop.h).
OPREC_SUBMIT, OPREC_CANCEL, OPREC_AMEND = 1, 2, 3

# Field byte budgets (the struct's fixed string boxes; the symbol box is
# exactly MAX_SYMBOL_BYTES — domain/order.py — so a record can never carry
# an identifier the engine would have to truncate).
SYMBOL_BYTES, CLIENT_ID_BYTES, ORDER_ID_BYTES = 64, 256, 36

OPREC_DTYPE = np.dtype([
    ("op", "u1"),
    ("side", "u1"),
    ("otype", "u1"),
    ("flags", "u1"),
    ("price_q4", "<i4"),
    ("quantity", "<i8"),
    ("symbol_len", "<u2"),
    ("client_id_len", "<u2"),
    ("order_id_len", "<u2"),
    # Shm multi-producer lane: me_shmring_commit stamps the committing
    # handle's writer id here (0 = anonymous/legacy). Every other edge
    # carries 0 — the old reserved pad, renamed, byte-identical.
    ("writer", "<u2"),
    ("symbol", f"S{SYMBOL_BYTES}"),
    ("client_id", f"S{CLIENT_ID_BYTES}"),
    ("order_id", f"S{ORDER_ID_BYTES}"),
    ("_pad2", "V4"),
])
assert OPREC_DTYPE.itemsize == RECORD_SIZE


# Raw byte offsets of the string boxes (field extraction would go through
# numpy's S-dtype scalar, which strips TRAILING NULs — identifiers like
# b"abc\x00" must round-trip exactly, so reads slice the raw record).
_SYM_OFF = OPREC_DTYPE.fields["symbol"][1]
_CID_OFF = OPREC_DTYPE.fields["client_id"][1]
_OID_OFF = OPREC_DTYPE.fields["order_id"][1]


def record_symbol(r) -> bytes:
    """One record's symbol bytes, exact (trailing NULs preserved)."""
    return r.tobytes()[_SYM_OFF:_SYM_OFF + int(r["symbol_len"])]


def record_order_id(r) -> bytes:
    """One record's order-id bytes, exact (trailing NULs preserved)."""
    return r.tobytes()[_OID_OFF:_OID_OFF + int(r["order_id_len"])]


class OpRecError(ValueError):
    """Malformed payload (bad magic / truncated / oversized). Raised by
    decode_payload for defects that poison the WHOLE batch; per-record
    flaws surface positionally via record_flaws instead."""


def _as_bytes(s) -> bytes:
    return s.encode() if isinstance(s, str) else bytes(s)


def pack_records(ops) -> np.ndarray:
    """Build a structured record array from op tuples.

    ops: iterable of (op, side, otype, price_q4, quantity, symbol,
    client_id, order_id) with str-or-bytes strings — the same tuple order
    the ring record uses (native_lanes.pack_record_batch minus the tag:
    batch payloads are positional, the tag is assigned server-side).
    """
    rows = list(ops)
    arr = np.zeros(len(rows), dtype=OPREC_DTYPE)
    for i, (op, side, otype, price_q4, qty, sym, cid, oid) in enumerate(rows):
        sym, cid, oid = _as_bytes(sym), _as_bytes(cid), _as_bytes(oid)
        if (len(sym) > SYMBOL_BYTES or len(cid) > CLIENT_ID_BYTES
                or len(oid) > ORDER_ID_BYTES):
            raise OpRecError(
                f"record {i}: identifier exceeds the fixed record box "
                f"(symbol<={SYMBOL_BYTES}, client_id<={CLIENT_ID_BYTES}, "
                f"order_id<={ORDER_ID_BYTES} bytes)")
        r = arr[i]
        r["op"], r["side"], r["otype"] = op, side, otype
        r["price_q4"], r["quantity"] = price_q4, qty
        r["symbol_len"], r["client_id_len"], r["order_id_len"] = (
            len(sym), len(cid), len(oid))
        r["symbol"], r["client_id"], r["order_id"] = sym, cid, oid
    return arr


def pack_submit_columns(sides, otypes, prices_q4, quantities, symbols,
                        client_ids) -> np.ndarray:
    """Vectorized submit-only builder (bench/replay generators): numeric
    columns land via bulk numpy assignment; the only per-op python is the
    byte-length scan for the string columns."""
    n = len(sides)
    arr = np.zeros(n, dtype=OPREC_DTYPE)
    arr["op"] = OPREC_SUBMIT
    arr["side"] = np.asarray(sides, dtype=np.uint8)
    arr["otype"] = np.asarray(otypes, dtype=np.uint8)
    arr["price_q4"] = np.asarray(prices_q4, dtype=np.int32)
    arr["quantity"] = np.asarray(quantities, dtype=np.int64)
    syms = [_as_bytes(s) for s in symbols]
    cids = [_as_bytes(c) for c in client_ids]
    arr["symbol"] = syms
    arr["client_id"] = cids
    arr["symbol_len"] = [len(s) for s in syms]
    arr["client_id_len"] = [len(c) for c in cids]
    return arr


def encode_payload(arr: np.ndarray) -> bytes:
    """Records -> one batch payload (the SubmitOrderBatch `ops` bytes and
    the recorded-op-file body): magic + packed records."""
    if arr.dtype != OPREC_DTYPE:
        arr = np.asarray(arr, dtype=OPREC_DTYPE)
    return MAGIC + arr.tobytes()


def decode_payload(payload: bytes, max_records: int | None = None
                   ) -> np.ndarray:
    """One batch payload -> records. Raises OpRecError on a malformed
    payload (wrong magic, truncated/ragged body, over the record cap) —
    the batch-poisoning defects; per-record problems are reported
    positionally by record_flaws so one bad op never fails the batch."""
    if len(payload) < HEADER_SIZE or payload[:HEADER_SIZE] != MAGIC:
        raise OpRecError("bad op-record magic (not an MEOPREC1 payload)")
    body = payload[HEADER_SIZE:]
    if len(body) % RECORD_SIZE != 0:
        raise OpRecError(
            f"truncated op-record payload ({len(body)} bytes is not a "
            f"multiple of the {RECORD_SIZE}-byte record)")
    n = len(body) // RECORD_SIZE
    if max_records is not None and n > max_records:
        raise OpRecError(
            f"op-record batch of {n} exceeds the per-request cap "
            f"{max_records}")
    return np.frombuffer(body, dtype=OPREC_DTYPE)


def record_flaws(arr: np.ndarray) -> list[str | None]:
    """Per-record EDGE validation, vectorized: a list of None (ok) or a
    reject message, positionally — everything decidable without engine
    state (codec structure, op codes, value ranges, the Q4 price lane
    bounds). Semantic checks (symbol ownership, auction mode, directory
    lookups) stay with the serving path that owns them. Flawed records
    never reach the native converter, whose structural guards would
    otherwise fail the WHOLE batch."""
    from matching_engine_tpu.domain.order import MAX_QUANTITY
    from matching_engine_tpu.domain.price import MAX_DEVICE_PRICE_Q4

    n = len(arr)
    msgs: list[str | None] = [None] * n
    op = arr["op"]
    bad_op = ~np.isin(op, (OPREC_SUBMIT, OPREC_CANCEL, OPREC_AMEND))
    bad_flags = arr["flags"] != 0
    bad_lens = ((arr["symbol_len"] > SYMBOL_BYTES)
                | (arr["client_id_len"] > CLIENT_ID_BYTES)
                | (arr["order_id_len"] > ORDER_ID_BYTES))
    is_submit = op == OPREC_SUBMIT
    is_target = (op == OPREC_CANCEL) | (op == OPREC_AMEND)
    no_symbol = is_submit & (arr["symbol_len"] == 0)
    no_target = is_target & (arr["order_id_len"] == 0)
    no_client = is_target & (arr["client_id_len"] == 0)
    bad_side = is_submit & ~np.isin(arr["side"], (1, 2))
    bad_otype = is_submit & (arr["otype"] > 4)  # collapsed device codes 0..4
    qty = arr["quantity"]
    bad_qty = (is_submit | (op == OPREC_AMEND)) & (qty <= 0)
    # Amends share the bound: an over-cap new_quantity could never be a
    # strict reduction of an in-cap order, and the i64 record field must
    # not reach the engine's int32 quantity lane.
    big_qty = (is_submit | (op == OPREC_AMEND)) & (qty > MAX_QUANTITY)
    # Priced collapsed codes (LIMIT=0 / LIMIT_IOC=2 / LIMIT_FOK=3) need a
    # positive in-lane Q4 price; market codes (1, 4) must carry 0 — the
    # record IS the engine tuple, there is no "ignored" price column.
    price = arr["price_q4"]
    priced = is_submit & np.isin(arr["otype"], (0, 2, 3))
    market = is_submit & np.isin(arr["otype"], (1, 4))
    bad_price = priced & ((price <= 0) | (price > MAX_DEVICE_PRICE_Q4))
    bad_mkt_price = market & (price != 0)
    for i in np.nonzero(bad_op | bad_flags | bad_lens | no_symbol
                        | no_target | no_client | bad_side | bad_otype
                        | bad_qty | big_qty | bad_price | bad_mkt_price)[0]:
        if bad_op[i]:
            msgs[i] = "invalid op code (1=submit, 2=cancel, 3=amend)"
        elif bad_flags[i]:
            msgs[i] = "reserved flags must be 0"
        elif bad_lens[i]:
            msgs[i] = "identifier length exceeds the record box"
        elif no_symbol[i]:
            msgs[i] = "symbol is required"
        elif no_target[i]:
            msgs[i] = "unknown order id"
        elif no_client[i]:
            msgs[i] = "client_id is required"
        elif bad_side[i]:
            msgs[i] = "side must be BUY or SELL"
        elif bad_otype[i]:
            msgs[i] = "unsupported (order_type, tif) combination"
        elif bad_qty[i]:
            msgs[i] = ("new_quantity must be positive"
                       if op[i] == OPREC_AMEND
                       else "quantity must be positive")
        elif big_qty[i]:
            msgs[i] = (f"quantity exceeds the engine maximum "
                       f"{MAX_QUANTITY} (int32 book-sum safety bound)")
        elif bad_price[i]:
            msgs[i] = (f"price_q4 out of the engine's int32 price lane "
                       f"(0, {MAX_DEVICE_PRICE_Q4}]")
        else:
            msgs[i] = "MARKET records must carry price_q4=0"
    return msgs


# -- shm ingress response records + reason vocabulary -------------------------
#
# The shared-memory edge (native/me_shmring.cpp) answers positionally
# through a ring of fixed 48-byte response records (MeShmResp in
# native/me_gwop.h; the ABI cross-checker pins this dtype against the C
# struct and the ctypes mirror). Rejects carry CODES, not free text —
# one vocabulary across the C++ structural screen (me_oprec_flaws), the
# vectorized admission pipeline (server/admission.py) and the client.

SHM_RESP_DTYPE = np.dtype([
    ("seq", "<u8"),
    ("remaining", "<i8"),
    ("order_id", "S24"),
    ("ok", "u1"),
    ("kind", "u1"),
    ("reason", "u1"),
    ("oid_len", "u1"),
    # Writer lane echoed from the request record: me_shmring_respond_n
    # routes the response into THIS writer's private sub-ring, and the
    # stamp lets a client self-check it only ever sees its own acks.
    ("writer", "u1"),
    ("_pad", "V3"),
])
assert SHM_RESP_DTYPE.itemsize == 48

# MeIngressReason (native/me_gwop.h) — the shm edge's reject vocabulary.
(REASON_NONE, REASON_MALFORMED, REASON_RATE, REASON_QTY, REASON_BAND,
 REASON_STP, REASON_RING_FULL, REASON_ENGINE, REASON_REJECTED) = range(9)

REASON_MESSAGES = {
    REASON_NONE: "",
    REASON_MALFORMED: "malformed record (structural screen)",
    REASON_RATE: "per-client rate limit exceeded",
    REASON_QTY: "order size exceeds the per-client maximum",
    REASON_BAND: "price outside the admission band",
    REASON_STP: "self-trade prevention (crosses own resting order)",
    REASON_RING_FULL: "server overloaded",
    REASON_ENGINE: "engine error",
    REASON_REJECTED: "rejected",
}

# me_oprec_flaws (me_lanes.cpp) code -> the record_flaws message branch.
# Code 9 depends on the op (amend vs submit wording); flaw_message
# resolves it. tests/test_shm_ingress.py pins code<->message parity by
# fuzzing both screens over the same records.
_FLAW_MESSAGES = {
    1: "invalid op code (1=submit, 2=cancel, 3=amend)",
    2: "reserved flags must be 0",
    3: "identifier length exceeds the record box",
    4: "symbol is required",
    5: "unknown order id",
    6: "client_id is required",
    7: "side must be BUY or SELL",
    8: "unsupported (order_type, tif) combination",
    11: None,  # price bound (built below: value-dependent)
    12: "MARKET records must carry price_q4=0",
}


def flaw_message(code: int, op: int) -> str | None:
    """me_oprec_flaws code -> the exact record_flaws message (None for
    code 0 / clean)."""
    from matching_engine_tpu.domain.order import MAX_QUANTITY
    from matching_engine_tpu.domain.price import MAX_DEVICE_PRICE_Q4

    if code == 0:
        return None
    if code == 9:
        return ("new_quantity must be positive" if op == OPREC_AMEND
                else "quantity must be positive")
    if code == 10:
        return (f"quantity exceeds the engine maximum "
                f"{MAX_QUANTITY} (int32 book-sum safety bound)")
    if code == 11:
        return (f"price_q4 out of the engine's int32 price lane "
                f"(0, {MAX_DEVICE_PRICE_Q4}]")
    return _FLAW_MESSAGES.get(code, "malformed record")


def record_fields(r) -> tuple:
    """One record -> the (op, side, otype, price_q4, quantity, symbol,
    client_id, order_id) tuple with length-sliced BYTES strings, read
    from the RAW record bytes at the field offsets: any numpy S-dtype
    field extraction strips TRAILING NULs, which would shorten an id
    like b"abc\\x00" to 3 bytes on the python path while the C++
    converter memcpys all 4 — embedded AND trailing NULs must
    round-trip identically (the MeGwOp contract; fuzz-pinned)."""
    raw = r.tobytes()
    return (int(r["op"]), int(r["side"]), int(r["otype"]),
            int(r["price_q4"]), int(r["quantity"]),
            raw[_SYM_OFF:_SYM_OFF + int(r["symbol_len"])],
            raw[_CID_OFF:_CID_OFF + int(r["client_id_len"])],
            raw[_OID_OFF:_OID_OFF + int(r["order_id_len"])])


# -- recorded op files --------------------------------------------------------
#
# A recorded flow is just a payload on disk: the CLI's submit-batch verb,
# the soak's codec-replay round, and the benches all read the same file
# through read_opfile and re-slice it into request payloads. Files may be
# gzip-compressed (records are sparse fixed boxes, ~50-100x): a ".gz"
# path writes compressed, and read_opfile sniffs the gzip magic so every
# consumer reads either form transparently. Compressed writes pin
# mtime=0 — a workload artifact's bytes must be a pure function of its
# records (the determinism contract tests/test_scenarios.py byte-compares
# on), never of the recording wall clock.

_GZIP_MAGIC = b"\x1f\x8b"


def write_opfile(path: str, arr: np.ndarray) -> None:
    payload = encode_payload(arr)
    if path.endswith(".gz"):
        import gzip

        with open(path, "wb") as raw:
            # filename="" + mtime=0: the container must not embed the
            # output path or the recording wall clock — artifact bytes
            # are a pure function of the records.
            with gzip.GzipFile(filename="", fileobj=raw, mode="wb",
                               mtime=0) as f:
                f.write(payload)
        return
    with open(path, "wb") as f:
        f.write(payload)


def read_opfile(path: str) -> np.ndarray:
    with open(path, "rb") as f:
        data = f.read()
    if data[:2] == _GZIP_MAGIC:
        import gzip

        data = gzip.decompress(data)
    return decode_payload(data)


def slice_payload(arr: np.ndarray, start: int, count: int) -> bytes:
    """Re-encode records [start, start+count) as one request payload —
    how a recorded file becomes a stream of SubmitOrderBatch calls."""
    return encode_payload(arr[start:start + count])
