"""Order value type and submit-time validation.

Mirrors the reference's construction-time invariant — an Order can only exist
with a Q4-normalized price (include/domain/order.hpp:15-28 routes every
construction through normalize_to_q4) — and the reference's validation /
reject semantics (src/server/matching_engine_service.cpp:66-83): rejects are
application-level (success=false + message over gRPC status OK), triggered by
missing symbol, non-positive quantity, or non-positive LIMIT price.

This framework adds one device-facing constraint: normalized Q4 prices must
fit the engine's int32 book lanes (see domain/price.py). Violations reject
with an overflow message, they never truncate.
"""

from __future__ import annotations

import dataclasses

from matching_engine_tpu.domain.price import (
    MAX_DEVICE_PRICE_Q4,
    PriceError,
    normalize_to_q4,
)
from matching_engine_tpu.proto import pb2

# Largest per-order quantity the engine accepts. Chosen so that a full book
# side's quantity sum stays below 2**31 for any capacity <= 1024: the device
# kernel accumulates quantity prefix-sums at int32 lane width
# (engine/kernel.py), so capacity * MAX_QUANTITY must not wrap.
MAX_QUANTITY = 2_000_000

# Identifier byte-length ceilings. Both bound host-side memory per order and
# keep every string representable in the native sink's u16 length-prefixed
# wire format (native/me_native.cpp §3).
MAX_SYMBOL_BYTES = 64
MAX_CLIENT_ID_BYTES = 256


class ValidationError(ValueError):
    """Submit-time rejection; `.message` is the client-visible error text."""

    @property
    def message(self) -> str:
        return str(self)


@dataclasses.dataclass(frozen=True)
class Order:
    """An accepted order, price always Q4-normalized.

    Use `Order.from_raw` — it is the only path that normalizes; constructing
    directly is reserved for already-normalized values (e.g. recovery from
    storage, which persists Q4).
    """

    order_id: str
    client_id: str
    symbol: str
    price_q4: int
    quantity: int
    side: int
    order_type: int = pb2.LIMIT

    @classmethod
    def from_raw(
        cls,
        order_id: str,
        client_id: str,
        symbol: str,
        price: int,
        scale: int,
        quantity: int,
        side: int,
        order_type: int = pb2.LIMIT,
    ) -> "Order":
        return cls(
            order_id=order_id,
            client_id=client_id,
            symbol=symbol,
            price_q4=normalize_to_q4(price, scale),
            quantity=quantity,
            side=side,
            order_type=order_type,
        )


def validate_submit(request: pb2.OrderRequest) -> str | None:
    """Validate an OrderRequest; returns a rejection message or None if OK.

    Ordering and conditions track the reference
    (matching_engine_service.cpp:66-83): symbol, then quantity, then LIMIT
    price positivity; plus this framework's side check and device price-range
    guard. Price normalization errors (bad scale / overflow) also reject.
    """
    if not request.symbol:
        return "symbol is required"
    if len(request.symbol.encode()) > MAX_SYMBOL_BYTES:
        return f"symbol exceeds {MAX_SYMBOL_BYTES} bytes"
    if len(request.client_id.encode()) > MAX_CLIENT_ID_BYTES:
        return f"client_id exceeds {MAX_CLIENT_ID_BYTES} bytes"
    if request.quantity <= 0:
        return "quantity must be positive"
    if request.quantity > MAX_QUANTITY:
        return (
            f"quantity {request.quantity} exceeds the engine maximum "
            f"{MAX_QUANTITY} (int32 book-sum safety bound)"
        )
    if request.side not in (pb2.BUY, pb2.SELL):
        return "side must be BUY or SELL"
    if request.order_type not in (pb2.LIMIT, pb2.MARKET):
        # proto3 open enums preserve unknown values; reject, don't guess.
        return "order_type must be LIMIT or MARKET"
    if request.order_type == pb2.LIMIT:
        if request.price <= 0:
            return "limit orders require a positive price"
        try:
            q4 = normalize_to_q4(request.price, request.scale)
        except PriceError as e:
            return str(e)
        if q4 <= 0:
            return "limit price normalizes to zero at Q4 resolution"
        if q4 > MAX_DEVICE_PRICE_Q4:
            return (
                f"normalized Q4 price {q4} exceeds the engine's int32 price "
                f"lane (max {MAX_DEVICE_PRICE_Q4})"
            )
    else:
        # MARKET orders carry no meaningful price; only the scale must parse.
        if not 0 <= request.scale <= 18:
            return f"scale {request.scale} out of range [0, 18]"
    return None


def owner_hash(client_id: str) -> int:
    """Stable int32 self-trade-prevention identity for a client id.

    Nonzero for every real client (0 is the kernel's "no owner" sentinel,
    which never suppresses a match); crc32 keeps it stable across runs and
    processes — the hash lives in device book lanes and checkpoints."""
    if not client_id:
        return 0
    import zlib

    h = zlib.crc32(client_id.encode()) & 0x7FFFFFFF
    return h or 1
