from matching_engine_tpu.domain.price import (
    K_TARGET_SCALE,
    MAX_DEVICE_PRICE_Q4,
    POW10,
    PriceError,
    normalize_to_q4,
    normalize_to_q4_jax,
)
from matching_engine_tpu.domain.order import Order, ValidationError, validate_submit
from matching_engine_tpu.domain.side import BUY, SELL, Side

__all__ = [
    "K_TARGET_SCALE",
    "MAX_DEVICE_PRICE_Q4",
    "POW10",
    "PriceError",
    "normalize_to_q4",
    "normalize_to_q4_jax",
    "Order",
    "ValidationError",
    "validate_submit",
    "BUY",
    "SELL",
    "Side",
]
