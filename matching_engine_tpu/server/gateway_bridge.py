"""GatewayBridge: glue between the C++ serving edge and the JAX engine.

With the native gateway (native/me_gateway.cpp) terminating gRPC, an
order's path is: C++ conn thread parses + validates + pushes a wide op
record into the gateway ring; THIS bridge thread drains time/size-windowed
batches, assigns ids/handles, runs the dense device dispatch, hands the
storage/stream events to the sink/hub, and completes each op back through
the gateway, which serializes and writes the response frames. Python code
runs only per-batch (directory bookkeeping + decode), never per-RPC — the
north-star serving shape (BASELINE.json: "host gRPC front end in C++,
batch dispatcher, JAX engine").

Forwarded methods (GetOrderBook / GetMetrics / the two server-streaming
RPCs) arrive on the gateway callback and are answered by the SAME
MatchingEngineService methods the grpcio edge uses — one implementation of
book snapshots, metrics, and stream fan-out, two transports.
"""

from __future__ import annotations

import queue
import threading
import time

from matching_engine_tpu.engine.kernel import (
    CANCELED,
    NEW,
    OP_AMEND,
    OP_CANCEL,
    OP_SUBMIT,
    REJECTED,
)
from matching_engine_tpu.proto import pb2
from matching_engine_tpu.server.dispatcher import publish_result
from matching_engine_tpu.server.engine_runner import EngineOp, OrderInfo
from matching_engine_tpu.utils.obs import DispatchTimeline, record_dispatch_error


class _StreamContext:
    """Duck-typed grpc context for service stream handlers: `is_active`
    polls the native stream's liveness."""

    def __init__(self, gateway, tag: int):
        self._gateway = gateway
        self._tag = tag

    def is_active(self) -> bool:
        return self._gateway.stream_alive(self._tag)

    def peer(self) -> str:
        return "native-gateway"


class GatewayBridge:
    def __init__(
        self,
        gateway,              # native.NativeGateway (created, not started)
        runner,
        service,              # MatchingEngineService (forwarded methods)
        sink=None,
        hub=None,
        window_ms: float = 2.0,
        max_batch: int | None = None,
        workers: int = 8,
        native_lanes: bool = False,
        shards=None,  # server/shards.ServingShards | None
    ):
        self.gateway = gateway
        self.runner = runner
        self.service = service
        self.sink = sink
        self.hub = hub
        self.metrics = runner.metrics
        # Partitioned serving: the drain loop routes each popped record to
        # its lane (submits by symbol shard, cancels/amends by the order
        # id's birth lane) and stages one dispatch per touched lane. Only
        # the python dispatch route composes with shards — the native-lane
        # drain hands whole raw buffers to ONE C++ engine.
        self.shards = shards
        if shards is not None and native_lanes:
            raise ValueError(
                "the gateway's native-lane drain is single-lane; with "
                "serve-shards use its python dispatch route")
        self.window_us = max(1, int(window_ms * 1e3))
        self.max_batch = max_batch or (runner.cfg.num_symbols * runner.cfg.batch)
        # Native lane mode (server/native_lanes.py): the drain loop pops
        # RAW MeGwOp buffers and hands them to the C++ lane engine — no
        # per-record Python decode, no EngineOp construction; completions
        # come back as one pre-packed complete_batch buffer. Requires a
        # NativeLanesRunner.
        self.native_lanes = native_lanes
        if native_lanes and not getattr(runner, "native_lanes", False):
            raise ValueError("native_lanes=True needs a NativeLanesRunner")
        self._stop = threading.Event()
        self._stream_threads: set[threading.Thread] = set()
        self._stream_lock = threading.Lock()
        self._fwd_q: queue.Queue = queue.Queue()
        self.gateway.set_callback(self._on_forwarded)
        # M_BATCH routing: by default the gateway runs the in-gateway
        # native batch path (structural screen + conversion + bulk ring
        # push, answered positionally from ring completions — no python
        # on the payload). The vectorized admission screens run
        # python-side only, so with them enabled batches forward through
        # the shared service handler instead.
        admission = getattr(service, "admission", None)
        set_fwd = getattr(self.gateway, "set_forward_batch", None)
        if set_fwd is not None:  # duck-typed test gateways skip it
            set_fwd(admission is not None and admission.enabled)
        self._drain_thread = threading.Thread(
            target=self._run_native if native_lanes else self._run,
            name="gw-bridge", daemon=True
        )
        self._workers = [
            threading.Thread(target=self._worker, name=f"gw-fwd-{i}", daemon=True)
            for i in range(workers)
        ]

    def start(self) -> int:
        port = self.gateway.start()
        self._drain_thread.start()
        for w in self._workers:
            w.start()
        return port

    def close(self) -> None:
        self._stop.set()
        self.gateway.shutdown()  # closes the ring -> drain thread exits
        self._drain_thread.join(timeout=10)
        for _ in self._workers:
            self._fwd_q.put(None)
        for w in self._workers:
            w.join(timeout=5)
        # Stream threads observe the dead connections (stream_alive -> False,
        # sub.stream polls at 250ms) and exit; they MUST be joined before the
        # C++ gateway is freed or a late respond() would touch freed memory.
        with self._stream_lock:
            streams = list(self._stream_threads)
        for t in streams:
            t.join(timeout=5)
        # A join timeout means a thread may still call into the gateway
        # (e.g. the drain thread mid-compile on a new batch shape): leak the
        # native object rather than free memory under a live thread — the
        # same policy as NativeRingDispatcher.close.
        stragglers = [
            t for t in [self._drain_thread, *self._workers, *streams]
            if t.is_alive()
        ]
        if stragglers:
            print(f"[gw-bridge] {len(stragglers)} thread(s) busy at close; "
                  f"leaking native gateway")
            return
        self.gateway.destroy()

    # -- hot path: the ring drain loop -------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                recs = self.gateway.pop_batch(
                    self.max_batch, self.window_us,
                    self.window_us if self._any_pending() else -1,
                )
            except Exception as e:  # noqa: BLE001 — a record that fails
                # host-side decode (e.g. a non-UTF-8 field surviving the C++
                # proto parse) must not kill the drain thread; its op is
                # dropped (client times out) but the edge stays up.
                self.metrics.inc("dispatch_errors")
                print(f"[gw-bridge] pop_batch failed: {type(e).__name__}: {e}")
                continue
            if recs is None:
                break
            if not recs:  # idle lull with a staged dispatch: finish it
                self._finish_all()
                continue
            try:
                self._drain_batch(recs)
            except Exception as e:  # noqa: BLE001 — the drain thread must
                # survive ANY per-batch failure (e.g. handle-space
                # exhaustion raising in the op-build loop): a dead drain
                # thread strands every gateway client until its deadline.
                self.metrics.inc("dispatch_errors")
                record_dispatch_error(self.metrics, "gw-bridge", e)
                print(f"[gw-bridge] batch failed: {type(e).__name__}: {e}")
                for rec in recs:
                    # Best effort: fail every op in the batch (completing a
                    # tag twice is a no-op — take_pending already removed it).
                    if rec[1] == 1:
                        self.gateway.complete_submit(
                            rec[0], False, "", "engine error")
                    elif rec[1] == 3:
                        self.gateway.complete_amend(
                            rec[0], False, rec[8] or "", 0, "engine error")
                    else:
                        # rec[8] is None for records that failed string
                        # decode — this fallback must never raise.
                        self.gateway.complete_cancel(
                            rec[0], False, rec[8] or "", "engine error")
        self._finish_all()

    def _any_pending(self) -> bool:
        if self.shards is None:
            return self.runner.has_pending
        return any(l.runner.has_pending for l in self.shards.lanes)

    def _finish_all(self) -> None:
        if self.shards is None:
            self.runner.finish_pending()
        else:
            self.shards.finish_pending()

    # -- hot path, native-lane mode ----------------------------------------

    def _run_native(self) -> None:
        while not self._stop.is_set():
            buf, n = self.gateway.pop_batch_raw(
                self.max_batch, self.window_us,
                self.window_us if self.runner.has_pending else -1,
            )
            if buf is None:
                break
            if n == 0:  # idle lull with a staged dispatch: finish it
                self.runner.finish_pending()
                continue
            try:
                self._drain_batch_native(buf, n)
            except Exception as e:  # noqa: BLE001 — the drain thread must
                # survive ANY per-batch failure; fail the batch's clients
                # instead of stranding them until their deadline.
                self.metrics.inc("dispatch_errors")
                record_dispatch_error(self.metrics, "gw-bridge-native", e)
                print(f"[gw-bridge] native batch failed: "
                      f"{type(e).__name__}: {e}")
                self._fail_records(buf, n)
        self.runner.finish_pending()

    def _fail_records(self, recs, n: int) -> None:
        """Best-effort engine-error completion for every record of a
        failed batch (completing a tag twice is a no-op)."""
        for i in range(n):
            r = recs[i]
            oid = bytes(r.order_id[:r.order_id_len]).decode(errors="replace")
            if r.op == 1:
                self.gateway.complete_submit(r.tag, False, "", "engine error")
            elif r.op == 3:
                self.gateway.complete_amend(r.tag, False, oid, 0,
                                            "engine error")
            else:
                self.gateway.complete_cancel(r.tag, False, oid,
                                             "engine error")

    def _drain_batch_native(self, buf, n: int) -> None:
        from matching_engine_tpu.server.native_lanes import (
            publish_native_result,
            snapshot_records,
        )

        t0 = time.perf_counter()
        # Stable copy (ONE memmove, not per-op Python): the pop buffer is
        # reused while this dispatch may still be staged, and the error
        # path needs the tags.
        recs = snapshot_records(buf, n)
        # Stage ledger for the C++-edge lane path. Ingress/ring-wait
        # happen inside the native gateway, so the ledger starts at the
        # pop boundary — the documented stamping point for this edge.
        tl = DispatchTimeline("gateway-lanes", n, t_pop=t0)

        def on_finish(result, error):
            # Same lock discipline as the Python path: publish under the
            # dispatch lock, complete clients from the returned thunk
            # after release.
            if error is not None:
                self.metrics.inc("dispatch_errors")
                tl.finish(self.metrics, error=error)
                print(f"[gw-bridge] native dispatch error: "
                      f"{type(error).__name__}: {error}")

                def fail():
                    self._fail_records(recs, n)
                return fail
            t_pub = time.perf_counter()
            dc = getattr(self.runner, "dropcopy", None)
            if dc is not None:
                dc.publish(result, tl)
            publish_native_result(result, self.sink, self.hub, self.metrics)
            self.metrics.ema_gauge(
                "bridge_publish_us", (time.perf_counter() - t_pub) * 1e6)
            tl.stamp_publish()
            tl.finish(self.metrics)

            def complete():
                # ONE ctypes crossing + one locked socket write per
                # connection for the whole dispatch — the comp buffer is
                # already in the complete_batch wire format.
                t_comp = time.perf_counter()
                self.gateway.complete_batch_raw(result.comp_buf)
                for (tag, ok, remaining, oid, err) in result.amends:
                    self.gateway.complete_amend(tag, ok, oid, remaining, err)
                self.metrics.ema_gauge(
                    "bridge_complete_us",
                    (time.perf_counter() - t_comp) * 1e6)
                dur_us = (time.perf_counter() - t0) * 1e6
                self.metrics.ema_gauge("dispatch_us", dur_us)
                self.metrics.observe("dispatch_us", dur_us)
                self.metrics.ema_gauge("dispatch_ops", n)
                stats = self.gateway.stats()
                self.metrics.set_gauge("gateway_requests", stats["requests"])
                self.metrics.set_gauge(
                    "gateway_ring_rejects", stats["ring_rejects"])
                self.metrics.set_gauge(
                    "gateway_connections", stats["conns"])
            return complete

        self.metrics.ema_gauge(
            "bridge_setup_us", (time.perf_counter() - t0) * 1e6)
        self.runner.dispatch_records(recs, n, on_finish, timeline=tl)

    def _drain_batch(self, recs) -> None:
        if self.shards is None:
            return self._drain_group(self.runner, recs)
        # Route by record, preserving per-lane arrival order (each group
        # keeps the ring's FIFO within its lane; cross-lane order was
        # never observable — different lanes are different books).
        groups: dict[int, list] = {}
        for rec in recs:
            if rec[1] == 1 and rec[6] is not None:
                lane = self.shards.lane_for_symbol(rec[6])
            elif rec[8]:
                lane = self.shards.lane_for_order(rec[8])
            else:
                lane = self.shards.lanes[0]  # decode-failed record:
                # completed with "invalid request encoding" in the group
            groups.setdefault(lane.shard_id, []).append(rec)
        for shard_id, group in groups.items():
            self._drain_group(self.shards.lanes[shard_id].runner, group)

    def _drain_group(self, runner, recs) -> None:
        t0 = time.perf_counter()
        ops: list[EngineOp] = []
        tags: dict[int, int] = {}  # id(EngineOp) -> gateway tag
        for (tag, op, side, otype, price_q4, qty, symbol, client_id,
             order_id) in recs:
            if symbol is None:  # failed host-side string decode (pop_batch)
                self.metrics.inc("orders_rejected")
                if op == 1:
                    self.gateway.complete_submit(
                        tag, False, "", "invalid request encoding")
                elif op == 3:
                    self.gateway.complete_amend(
                        tag, False, "", 0, "invalid request encoding")
                else:
                    self.gateway.complete_cancel(
                        tag, False, "", "invalid request encoding")
                continue
            if op == 1:  # submit (already validated in C++)
                if runner.auction_mode and otype != 0:  # anything but GTC LIMIT
                    self.metrics.inc("orders_rejected")
                    self.gateway.complete_submit(
                        tag, False, "",
                        "only GTC LIMIT orders are accepted during an "
                        "auction call period",
                    )
                    continue
                if not runner.owns_symbol(symbol):
                    self.metrics.inc("orders_rejected")
                    self.gateway.complete_submit(
                        tag, False, "",
                        f"symbol {symbol} is homed on another host",
                    )
                    continue
                if runner.slot_acquire(symbol) is None:
                    self.metrics.inc("orders_rejected")
                    self.gateway.complete_submit(
                        tag, False, "",
                        "symbol capacity exhausted (engine symbol axis is full)",
                    )
                    continue
                oid_num, oid_str = runner.assign_oid()
                info = OrderInfo(
                    oid=oid_num, order_id=oid_str, client_id=client_id,
                    symbol=symbol, side=side, otype=otype,
                    price_q4=price_q4, quantity=qty, remaining=qty,
                    status=0, handle=runner.assign_handle(),
                )
                # Always OP_SUBMIT: the runner classifies auction-mode
                # rests under the dispatch lock (edge reads would race
                # the RunAuction mode flip).
                e = EngineOp(OP_SUBMIT, info)
            elif op == 3:  # amend — same directory checks as the service
                info = runner.orders_by_id.get(order_id)
                if info is None:
                    self.gateway.complete_amend(
                        tag, False, order_id, 0, "unknown order id")
                    continue
                if info.client_id != client_id:
                    self.gateway.complete_amend(
                        tag, False, order_id, 0,
                        "order belongs to a different client")
                    continue
                e = EngineOp(OP_AMEND, info, amend_qty=qty)
            else:  # cancel — host-side directory checks, as the service does
                info = runner.orders_by_id.get(order_id)
                if info is None:
                    self.gateway.complete_cancel(
                        tag, False, order_id, "unknown order id"
                    )
                    continue
                if info.client_id != client_id:
                    self.gateway.complete_cancel(
                        tag, False, order_id,
                        "order belongs to a different client",
                    )
                    continue
                e = EngineOp(OP_CANCEL, info, cancel_requester=client_id)
            ops.append(e)
            tags[id(e)] = tag

        if not ops:
            return
        # Stage ledger: ingress/ring-wait live in the C++ gateway, so the
        # stamping starts at the pop boundary (t0 covers the per-op build
        # loop above inside the lane-build stage).
        tl = DispatchTimeline("gateway", len(ops), t_pop=t0)

        def on_finish(result, error):
            # Runs under the dispatch lock when this batch decodes (same
            # lock discipline as BatchDispatcher: sink/hub enqueue under
            # the lock so checkpoints see an untorn (book, SQLite,
            # snapshot) state). The returned thunk runs after release —
            # gateway completions write sockets and must not hold the
            # engine lock against a window-starved client.
            if error is not None:
                self.metrics.inc("dispatch_errors")
                tl.finish(self.metrics, error=error)
                print(f"[gw-bridge] dispatch error: "
                      f"{type(error).__name__}: {error}")

                def fail():
                    for op in ops:
                        tag = tags.get(id(op))
                        if tag is None:
                            continue
                        if op.op == OP_AMEND:
                            self.gateway.complete_amend(
                                tag, False, op.info.order_id, 0,
                                "engine error")
                        elif op.op != OP_CANCEL:
                            self.gateway.complete_submit(
                                tag, False, op.info.order_id, "engine error"
                            )
                        else:
                            self.gateway.complete_cancel(
                                tag, False, op.info.order_id, "engine error"
                            )
                return fail
            t_pub = time.perf_counter()
            dc = getattr(runner, "dropcopy", None)
            if dc is not None:
                # The GROUP's lane publisher (its runner carries the
                # auction-mode context the crossed-book check needs),
                # BEFORE the sink sees — and may coalesce-extend — the
                # row lists the drop-copy snapshots.
                dc.publish(result, tl)
            self._publish(result)
            self.metrics.ema_gauge(
                "bridge_publish_us", (time.perf_counter() - t_pub) * 1e6)
            tl.stamp_publish()
            tl.finish(self.metrics)

            def complete():
                # One ctypes crossing + one locked socket write per
                # CONNECTION for the whole dispatch (gateway.complete_batch)
                # — the per-op fan-out measured ~59us/op, the edge's
                # dominant cost at saturation (bridge_complete_us gauge).
                t_comp = time.perf_counter()
                batch: list[tuple[int, int, bool, str, str]] = []
                for outcome in result.outcomes:
                    tag = tags.pop(id(outcome.op), None)
                    if tag is None:
                        continue
                    info = outcome.op.info
                    if outcome.op.op == OP_AMEND:
                        # AmendResponse carries the new remaining: its own
                        # completion entry, outside the submit/cancel batch.
                        ok = outcome.status == NEW
                        if ok:
                            self.metrics.inc("orders_amended")
                        self.gateway.complete_amend(
                            tag, ok, info.order_id, outcome.remaining,
                            "" if ok else (outcome.error or "amend rejected"))
                    elif outcome.op.op != OP_CANCEL:
                        if outcome.status == REJECTED and outcome.error:
                            self.metrics.inc("orders_rejected")
                            batch.append(
                                (tag, 0, False, info.order_id, outcome.error))
                        else:
                            self.metrics.inc("orders_accepted")
                            batch.append((tag, 0, True, info.order_id, ""))
                    else:
                        if outcome.status == CANCELED:
                            self.metrics.inc("orders_canceled")
                            batch.append((tag, 1, True, info.order_id, ""))
                        else:
                            batch.append(
                                (tag, 1, False, info.order_id,
                                 outcome.error or "order not open"))
                # Any op that produced no outcome: fail loudly rather than
                # hang the client until its deadline.
                for op in ops:
                    tag = tags.pop(id(op), None)
                    if tag is None:
                        continue
                    if op.op == OP_AMEND:
                        self.gateway.complete_amend(
                            tag, False, op.info.order_id, 0,
                            "op produced no outcome")
                        continue
                    kind = 1 if op.op == OP_CANCEL else 0
                    batch.append((tag, kind, False, op.info.order_id,
                                  "op produced no outcome"))
                self.gateway.complete_batch(batch)
                self.metrics.ema_gauge(
                    "bridge_complete_us",
                    (time.perf_counter() - t_comp) * 1e6)
                # Batch TURNAROUND incl. pipeline residency (see
                # dispatcher.py) — engine time is engine_dispatch_us.
                dur_us = (time.perf_counter() - t0) * 1e6
                self.metrics.ema_gauge("dispatch_us", dur_us)
                self.metrics.observe("dispatch_us", dur_us)
                self.metrics.ema_gauge("dispatch_ops", len(recs))
                # Surface the C++ edge's counters through GetMetrics.
                stats = self.gateway.stats()
                self.metrics.set_gauge("gateway_requests", stats["requests"])
                self.metrics.set_gauge(
                    "gateway_ring_rejects", stats["ring_rejects"])
                self.metrics.set_gauge(
                    "gateway_connections", stats["conns"])
            return complete

        # Per-stage decomposition of the edge tax (BENCH_METHOD.md: the
        # full-stack gap to the RPC-less ceiling): setup = ring decode +
        # validation + OrderInfo/id assignment, publish = sink/hub
        # enqueue, complete = response fan-out through the gateway.
        self.metrics.ema_gauge(
            "bridge_setup_us", (time.perf_counter() - t0) * 1e6)
        runner.dispatch_pipelined(ops, on_finish, timeline=tl)

    def _publish(self, result) -> None:
        publish_result(result, self.sink, self.hub, self.metrics)

    # -- forwarded methods (book / metrics / streams) ----------------------

    def _on_forwarded(self, tag: int, method: int, payload: bytes) -> None:
        # Runs on a C++ connection thread: enqueue and return immediately.
        self._fwd_q.put((tag, method, payload))

    def _worker(self) -> None:
        from matching_engine_tpu import native as me_native

        while True:
            item = self._fwd_q.get()
            if item is None:
                return
            tag, method, payload = item
            try:
                if method == me_native.GW_BOOK:
                    req = pb2.OrderBookRequest.FromString(payload)
                    resp = self.service.GetOrderBook(req, None)
                    self.gateway.respond(tag, resp.SerializeToString(), True)
                elif method == me_native.GW_METRICS:
                    req = pb2.MetricsRequest.FromString(payload)
                    resp = self.service.GetMetrics(req, None)
                    self.gateway.respond(tag, resp.SerializeToString(), True)
                elif method == me_native.GW_AUCTION:
                    req = pb2.AuctionRequest.FromString(payload)
                    resp = self.service.RunAuction(req, None)
                    self.gateway.respond(tag, resp.SerializeToString(), True)
                elif method == me_native.GW_BATCH:
                    # Batch verb on the C++ edge: the gateway forwards the
                    # request whole (the op-record payload is already the
                    # flat binary the engine wants) and the SAME service
                    # handler that serves the grpcio edge splits, routes,
                    # and dispatches it — one implementation per verb,
                    # two transports.
                    req = pb2.OrderBatchRequest.FromString(payload)
                    resp = self.service.SubmitOrderBatch(req, None)
                    self.gateway.respond(tag, resp.SerializeToString(), True)
                elif method in (me_native.GW_STREAM_MD, me_native.GW_STREAM_OU):
                    # Streams hold a worker for their lifetime; run each on
                    # its own thread so they can't starve unary forwards.
                    t = threading.Thread(
                        target=self._stream, args=(tag, method, payload),
                        name=f"gw-stream-{tag}", daemon=True,
                    )
                    with self._stream_lock:
                        self._stream_threads.add(t)
                    t.start()
                else:
                    self.gateway.respond(
                        tag, None, True, grpc_status=12,
                        grpc_message="unknown forwarded method",
                    )
            except Exception as e:  # noqa: BLE001
                self.gateway.respond(
                    tag, None, True, grpc_status=13,
                    grpc_message=f"{type(e).__name__}: {e}",
                )

    def _stream(self, tag: int, method: int, payload: bytes) -> None:
        try:
            self._stream_impl(tag, method, payload)
        finally:
            with self._stream_lock:
                self._stream_threads.discard(threading.current_thread())

    def _stream_impl(self, tag: int, method: int, payload: bytes) -> None:
        from matching_engine_tpu import native as me_native

        ctx = _StreamContext(self.gateway, tag)
        try:
            if method == me_native.GW_STREAM_MD:
                req = pb2.MarketDataRequest.FromString(payload)
                it = self.service.StreamMarketData(req, ctx)
            else:
                req = pb2.OrderUpdatesRequest.FromString(payload)
                it = self.service.StreamOrderUpdates(req, ctx)
            try:
                for msg in it:
                    if not self.gateway.respond(tag, msg.SerializeToString(), False):
                        return  # stream gone
                self.gateway.respond(tag, None, True)  # server-side close
            finally:
                it.close()  # run the service generator's unsubscribe now
        except Exception as e:  # noqa: BLE001
            self.gateway.respond(
                tag, None, True, grpc_status=13,
                grpc_message=f"{type(e).__name__}: {e}",
            )
