"""Tiered capacity classes: one runner, K capacity-tier book groups.

The resident kernel's throughput was always quoted at a FIXED capacity per
book (128), and that capacity is a correctness wall: order 129 on a deep
book rejects. Real venues hold thousands of resting orders on hot symbols
while the tail idles near-empty — paying [S, 8192] lanes for every symbol
to serve a handful of deep books is exactly the waste the tier spec
removes (ROADMAP Open item 5).

`EngineConfig.tiers` partitions the symbol axis into contiguous groups,
each with its own capacity; this runner owns one device book PER TIER and
steps each tier group through its own jit'd kernel (vmapped over that
tier's symbols only). Dispatch building is unchanged — the host still
builds global [S, B, 7] waves — and the tier split is row slicing: tier t
sees rows [lo_t, lo_t + n_t), a zero-copy contiguous view. Waves with no
real ops for a tier skip that tier's device call entirely, so a dispatch
touching only hot symbols costs one small step, not T. Decoded results
and fills merge back in ascending tier order, which IS global
(symbol, batch-row) device order — bit-identical to an untiered runner
over the same (symbol -> slot, capacity) layout, pinned by
tests/test_tiers.py.

Symbol -> tier assignment is static at boot: `--book-tiers` pins named
symbols to groups; unpinned symbols allocate from the LAST (shallowest)
group first and spill toward deeper groups only when it fills — deep
tiers are for the pinned hot symbols, the tail gets standard books, and
a burst of new names borrows deep slots rather than rejecting.

Composition rules: serving shards split the tier spec proportionally
(every tier count divisible by K — server/shards.py); --native-lanes,
--mesh, and the sparse dispatch shape are refused/skipped (the tiered
_prepare always runs dense or mega). Checkpoints store one block per
tier, and the tier spec rides semantic_key: a store checkpointed under
one spec REFUSES to restore under another (clear error; boot falls back
to full replay, which re-rests orders into the new layout).

The backpressure story this enables: a full book is a metered positional
reject (me_book_capacity_rejects_total + per-tier series) and the
per-tier high-watermark gauges (me_book_depth_hwm*) tell the operator
which group to deepen — capacity stops being a silent correctness hazard.
"""

from __future__ import annotations

import bisect

import jax
import numpy as np

from matching_engine_tpu.engine.book import EngineConfig, init_book
from matching_engine_tpu.engine.harness import (
    DenseDecoded,
    HostFill,
    HostResult,
    batch_view,
    build_batch_arrays,
    decode_fills,
    decode_results,
    decode_step_mega,
)
from matching_engine_tpu.engine.kernel import engine_step_packed
from matching_engine_tpu.proto import pb2
from matching_engine_tpu.server.engine_runner import (
    DispatchResult,
    EngineRunner,
)
from matching_engine_tpu.utils.tracing import step_annotation


def parse_book_tiers(spec: str, num_symbols: int):
    """Parse a --book-tiers spec into (tiers, pins).

    Spec grammar: comma-separated groups `<count>x<capacity>` (one group
    may use `*` for count = every remaining symbol row), each optionally
    pinning symbols with `:<sym>;<sym>;...`. Example::

        --book-tiers "8x8192:HOT-0;HOT-1,56x1024,*x128"

    Returns (((count, capacity), ...), {symbol: group_index}). Raises
    ValueError on malformed specs or counts that do not cover the symbol
    axis exactly.
    """
    groups: list[tuple[int | None, int]] = []
    pins: dict[str, int] = {}
    if not spec.strip():
        raise ValueError("empty --book-tiers spec")
    for gi, part in enumerate(spec.split(",")):
        part = part.strip()
        body, _, pinned = part.partition(":")
        try:
            count_s, cap_s = body.split("x", 1)
            count = None if count_s.strip() == "*" else int(count_s)
            cap = int(cap_s)
        except ValueError:
            raise ValueError(
                f"malformed --book-tiers group {part!r} "
                "(want <count>x<capacity>[:SYM;SYM...])") from None
        if cap < 1 or (count is not None and count < 1):
            raise ValueError(f"non-positive tier in {part!r}")
        groups.append((count, cap))
        for sym in filter(None, (s.strip() for s in pinned.split(";"))):
            if sym in pins:
                raise ValueError(f"symbol {sym!r} pinned to two tiers")
            pins[sym] = gi
    stars = [i for i, (n, _) in enumerate(groups) if n is None]
    if len(stars) > 1:
        raise ValueError("at most one '*' tier group")
    fixed = sum(n for n, _ in groups if n is not None)
    if stars:
        rest = num_symbols - fixed
        if rest < 1:
            raise ValueError(
                f"fixed tier counts ({fixed}) leave no rows for the '*' "
                f"group of --symbols {num_symbols}")
        groups[stars[0]] = (rest, groups[stars[0]][1])
    elif fixed != num_symbols:
        raise ValueError(
            f"tier counts sum to {fixed}, --symbols is {num_symbols}")
    return tuple((int(n), int(c)) for n, c in groups), pins


class TieredEngineRunner(EngineRunner):
    """EngineRunner over per-tier device books (cfg.tiers non-empty).

    Single-process, python/EngineOp serving path only (native lanes and
    the mesh are refused at build time); composes with --serve-shards via
    a proportional per-lane tier split."""

    def __init__(self, cfg: EngineConfig, metrics=None, hub=None,
                 pipeline_inflight: int = 2, oid_offset: int = 0,
                 oid_stride: int = 1, device=None, owns_filter=None,
                 megadispatch_max_waves: int = 1, tier_pins=None):
        assert cfg.tiers, "TieredEngineRunner needs cfg.tiers"
        super().__init__(cfg, metrics, mesh=None, hub=hub,
                         pipeline_inflight=pipeline_inflight,
                         oid_offset=oid_offset, oid_stride=oid_stride,
                         device=device, owns_filter=owns_filter,
                         megadispatch_max_waves=megadispatch_max_waves)
        self.tier_cfgs = cfg.tier_configs()
        lo, los = 0, []
        for tcfg in self.tier_cfgs:
            los.append(lo)
            lo += tcfg.num_symbols
        self.tier_lo = los                       # group start slots
        self.tier_books = []
        for tcfg in self.tier_cfgs:
            b = init_book(tcfg)
            if device is not None:
                b = jax.device_put(b, device)
            self.tier_books.append(b)
        # Static symbol -> group pinning; unpinned symbols allocate from
        # the last group and spill toward group 0 (see module docstring).
        self.tier_pins = dict(tier_pins or {})
        for sym, g in self.tier_pins.items():
            if not (0 <= g < len(self.tier_cfgs)):
                raise ValueError(f"pin {sym!r} -> tier {g} out of range")
        # Per-group slot allocators (replace the base linear allocator).
        self._g_next = list(self.tier_lo)
        self._g_free: list[list[int]] = [[] for _ in self.tier_cfgs]
        # Unpinned allocation order: shallowest capacity first (spec
        # position breaks ties), regardless of how the spec is ordered.
        self._shallow_first = sorted(
            range(len(self.tier_cfgs)),
            key=lambda g: (self.tier_cfgs[g].capacity, g))
        # Per-group live-order high watermark (the re-tiering signal).
        self._depth_hwm = [0] * len(self.tier_cfgs)

    # -- tier geometry -----------------------------------------------------

    def tier_of_slot(self, slot: int) -> int:
        return bisect.bisect_right(self.tier_lo, slot) - 1

    def _tier_span(self, t: int) -> tuple[int, int]:
        lo = self.tier_lo[t]
        return lo, lo + self.tier_cfgs[t].num_symbols

    # -- slot allocation (per-group) ---------------------------------------

    def _slot_locked(self, symbol: str) -> int | None:
        slot = self.symbols.get(symbol)
        if slot is not None:
            return slot
        pin = self.tier_pins.get(symbol)
        # Pinned symbols allocate ONLY in their group (a full pinned group
        # is the same "symbol capacity exhausted" reject as a full axis);
        # unpinned search shallow-to-deep BY CAPACITY (not spec position —
        # a shallow-first spec must not invert the policy) so deep rows
        # stay available for pins and genuine spill.
        order = ([pin] if pin is not None else self._shallow_first)
        for g in order:
            if self._g_free[g]:
                slot = self._g_free[g].pop()
                break
            lo, hi = self._tier_span(g)
            if self._g_next[g] < hi:
                slot = self._g_next[g]
                self._g_next[g] += 1
                break
        else:
            return None
        self.symbols[symbol] = slot
        self.slot_symbols[slot] = symbol
        return slot

    def _recycle_slot(self, slot: int) -> None:
        self._g_free[self.tier_of_slot(slot)].append(slot)

    def slot_acquire(self, symbol: str) -> int | None:
        slot = super().slot_acquire(symbol)
        if slot is not None:
            # High-watermark of live orders per tier group — the
            # operator's re-tiering signal. _slot_live counts open AND
            # in-flight orders, a slight over-estimate of resting depth
            # (documented with the gauge). Under the id lock: concurrent
            # RPC threads race the read-modify-write otherwise.
            with self._id_lock:
                g = self.tier_of_slot(slot)
                d = self._slot_live[slot]
                if d > self._depth_hwm[g]:
                    self._depth_hwm[g] = d
                    self.metrics.set_gauge(f"book_depth_hwm_tier{g}", d)
                    self.metrics.set_gauge("book_depth_hwm",
                                           max(self._depth_hwm))
        return slot

    def rebuild_slot_allocator(self) -> None:
        for g in range(len(self.tier_cfgs)):
            lo, hi = self._tier_span(g)
            used = [s for s in self.symbols.values() if lo <= s < hi]
            nxt = max(lo, 1 + max(used, default=lo - 1))
            self._g_next[g] = min(nxt, hi)
            self._g_free[g] = [s for s in range(lo, self._g_next[g])
                               if self.slot_symbols[s] is None]

    # -- book placement / read-only views ----------------------------------

    def place_book(self, host_books) -> None:
        """Install per-tier host BookBatches (checkpoint restore)."""
        assert len(host_books) == len(self.tier_cfgs)
        self.tier_books = [
            jax.device_put(b, self.device) if self.device is not None
            else jax.device_put(b)
            for b in host_books
        ]

    def _snapshot_row(self, slot: int):
        t = self.tier_of_slot(slot)
        b = self.tier_books[t]
        r = slot - self.tier_lo[t]
        with self._snapshot_lock:
            return [
                np.asarray(x[r])
                for x in (b.bid_price, b.bid_qty, b.bid_oid, b.bid_seq,
                          b.ask_price, b.ask_qty, b.ask_oid, b.ask_seq)
            ]

    def _live_lane_qtys(self) -> dict[int, int]:
        lanes: dict[int, int] = {}
        with self._snapshot_lock:
            arrs = [
                (np.asarray(b.bid_oid), np.asarray(b.bid_qty),
                 np.asarray(b.ask_oid), np.asarray(b.ask_qty))
                for b in self.tier_books
            ]
        for bo, bq, ao, aq in arrs:
            for oid_arr, qty_arr in ((bo, bq), (ao, aq)):
                mask = qty_arr > 0
                for h, q in zip(oid_arr[mask].tolist(),
                                qty_arr[mask].tolist()):
                    lanes[int(h)] = int(q)
        return lanes

    def _crossed_blocks(self):
        out = []
        imin, imax = np.iinfo(np.int32).min, np.iinfo(np.int32).max
        for t, b in enumerate(self.tier_books):
            with self._snapshot_lock:
                bp, bq = np.asarray(b.bid_price), np.asarray(b.bid_qty)
                ap, aq = np.asarray(b.ask_price), np.asarray(b.ask_qty)
            best_bid = np.where(bq > 0, bp, imin).max(axis=1)
            best_ask = np.where(aq > 0, ap, imax).min(axis=1)
            crossed = ((bq > 0).any(axis=1) & (aq > 0).any(axis=1)
                       & (best_bid >= best_ask))
            out.append((self.tier_lo[t], crossed))
        return out

    def maybe_rebase_seqs(self) -> bool:
        from matching_engine_tpu.engine.maintenance import (
            REBASE_THRESHOLD,
            rebase_seqs,
        )

        did = False
        for t, tcfg in enumerate(self.tier_cfgs):
            mx = int(np.max(np.asarray(self.tier_books[t].next_seq)))
            if mx < REBASE_THRESHOLD:
                continue
            with self._snapshot_lock:
                self.tier_books[t] = rebase_seqs(tcfg, self.tier_books[t])
            self.metrics.inc("seq_rebases")
            did = True
        return did

    # -- dispatch shapes ----------------------------------------------------

    def _prepare(self, ops, host_orders, by_handle,
                 res: DispatchResult, terminal_makers: set[int],
                 timeline=None):
        """Dense/mega only: every wave is the global [S, B, 7] array,
        row-sliced per tier (a contiguous zero-copy view); tiers with no
        real ops in a wave skip their device call. Per-wave decode merges
        the tier outputs in ascending tier order == global (symbol,
        batch-row) device order, so all host consequences are identical
        to an untiered runner over the same layout. (The sparse shape is
        intentionally skipped: per-tier coordinate re-bucketing would buy
        back per-op host work the tier split exists to avoid.)"""
        if host_orders:
            self.metrics.inc("dense_dispatches")
        arrays = build_batch_arrays(self.cfg, host_orders)
        if self.megadispatch_max_waves > 1 and len(arrays) > 1:
            return self._prepare_mega_tiered(
                arrays, by_handle, res, terminal_makers, timeline=timeline)
        if timeline is not None:
            timeline.shape = "dense"
        n_tiers = len(self.tier_cfgs)
        touched_syms: set[int] = set()
        last_dec: list = [None] * n_tiers

        def dispatch():
            for arr in arrays:
                self._step_num += 1
                outs: list = [None] * n_tiers
                with self._snapshot_lock, step_annotation(
                        "engine_step", self._step_num):
                    for t, tcfg in enumerate(self.tier_cfgs):
                        lo, hi = self._tier_span(t)
                        sub = arr[lo:hi]
                        if not sub[:, :, 0].any():
                            continue
                        self.tier_books[t], pout = engine_step_packed(
                            tcfg, self.tier_books[t], sub)
                        outs[t] = (sub, pout)
                        try:
                            pout.small.copy_to_host_async()
                        except (AttributeError, RuntimeError):
                            pass
                yield outs

        def decode(outs):
            results: list = []
            fills: list = []
            overflow = False
            for t, item in enumerate(outs):
                if item is None:
                    continue
                sub, pout = item
                tcfg, lo = self.tier_cfgs[t], self.tier_lo[t]
                dec = DenseDecoded(tcfg, np.asarray(pout.small))
                results.extend(decode_results(
                    batch_view(sub), dec.status, dec.filled, dec.remaining,
                    sym_offset=lo))
                fills.extend(self._decode_tier_fills(
                    dec.fill_count, dec.fills_inline, pout.fills, lo))
                self.metrics.inc(
                    "readback_bytes",
                    pout.small.size * 4
                    + (pout.fills.size * 4
                       if dec.fill_count > dec.fills_inline.shape[1]
                       else 0))
                overflow = overflow or dec.fill_overflow
                last_dec[t] = dec
            self._account(results, fills, overflow, by_handle, res,
                          terminal_makers)
            touched_syms.update(r.sym for r in results)

        def finalize():
            self._tiered_market_data(touched_syms, last_dec, res)

        return len(arrays), dispatch(), decode, finalize

    def _decode_tier_fills(self, count, inline, full_buf, lo):
        if count == 0:
            return []
        packed = (inline if count <= inline.shape[1]
                  else np.asarray(full_buf))
        fills = decode_fills(packed[0], packed[1], packed[2], packed[3],
                             packed[4], count)
        if lo == 0:
            return fills
        return [HostFill(f.sym + lo, f.taker_oid, f.maker_oid, f.price_q4,
                         f.quantity) for f in fills]

    def _tiered_market_data(self, touched_syms, last_dec, res) -> None:
        if not touched_syms or not self._build_md:
            return
        for s in touched_syms:
            t = self.tier_of_slot(s)
            dec = last_dec[t]
            sym = self.slot_symbols[s]
            if dec is None or sym is None:
                continue
            i = s - self.tier_lo[t]
            res.market_data.append(pb2.MarketDataUpdate(
                symbol=sym,
                best_bid=int(dec.best_bid[i]),
                best_ask=int(dec.best_ask[i]),
                scale=4,
                bid_size=int(dec.bid_size[i]),
                ask_size=int(dec.ask_size[i]),
            ))

    def _prepare_mega_tiered(self, arrays, by_handle, res: DispatchResult,
                             terminal_makers: set[int], timeline=None):
        """Megadispatch per tier: each chunk of up to M waves stacks
        per-tier row slices into per-tier [M, S_t, B, 7] scans. Decode
        merges tier outputs PER WAVE (ascending tier order), replaying
        the exact serial event order."""
        from matching_engine_tpu.engine import kernel as _kernel

        m_cap = self.megadispatch_max_waves
        if timeline is not None:
            timeline.shape = "mega"
            timeline.mega_m = min(m_cap, len(arrays))
        chunks = [arrays[i:i + m_cap] for i in range(0, len(arrays), m_cap)]
        n_tiers = len(self.tier_cfgs)
        touched_syms: set[int] = set()
        last_dec: list = [None] * n_tiers

        def dispatch():
            for group in chunks:
                m = len(group)
                self._step_num += 1
                outs: list = [None] * n_tiers
                with self._snapshot_lock, step_annotation(
                        "engine_step_mega", self._step_num):
                    for t, tcfg in enumerate(self.tier_cfgs):
                        lo, hi = self._tier_span(t)
                        subs = [a[lo:hi] for a in group]
                        deepest = max(
                            int(np.count_nonzero(s[:, :, 0])) for s in subs)
                        if deepest == 0:
                            continue
                        rcap = _kernel.mega_result_cap(tcfg, deepest)
                        self.tier_books[t], mout = _kernel.engine_step_mega(
                            tcfg, self.tier_books[t], np.stack(subs), rcap)
                        outs[t] = (m, rcap, mout)
                        try:
                            mout.small.copy_to_host_async()
                        except (AttributeError, RuntimeError):
                            pass
                self.metrics.inc("megadispatch_steps")
                self.metrics.inc("megadispatch_stacked_waves", m)
                yield m, outs

        def decode(item):
            m, outs = item
            per_tier: list = [None] * n_tiers
            for t, out in enumerate(outs):
                if out is None:
                    continue
                _, rcap, mout = out
                tcfg = self.tier_cfgs[t]
                waves, dec, fetched_full = decode_step_mega(
                    tcfg, mout, m, rcap)
                self.metrics.inc(
                    "readback_bytes",
                    mout.small.size * 4
                    + (mout.fills.size * 4 if fetched_full else 0))
                per_tier[t] = waves
                last_dec[t] = dec
            for w in range(m):
                results: list = []
                fills: list = []
                overflow = False
                for t, waves in enumerate(per_tier):
                    if waves is None:
                        continue
                    r, f, ov = waves[w]
                    lo = self.tier_lo[t]
                    if lo:
                        r = [HostResult(x.oid, x.sym + lo, x.status,
                                        x.filled, x.remaining) for x in r]
                        f = [HostFill(x.sym + lo, x.taker_oid, x.maker_oid,
                                      x.price_q4, x.quantity) for x in f]
                    results.extend(r)
                    fills.extend(f)
                    overflow = overflow or ov
                self._account(results, fills, overflow, by_handle, res,
                              terminal_makers)
                touched_syms.update(r.sym for r in results)

        def finalize():
            self._tiered_market_data(touched_syms, last_dec, res)

        return len(arrays), dispatch(), decode, finalize

    # -- auction ------------------------------------------------------------

    def _auction_device(self, mask):
        """One uncross per tier group (per-tier all-or-nothing, mirroring
        the mesh path's per-shard abort semantics); outputs concatenate
        in tier order into the global [S] view the shared summary code
        reads."""
        from matching_engine_tpu.engine.auction import (
            auction_step,
            decode_auction,
        )

        parts: list = []
        fills_all: list = []
        flags: list[bool] = []
        aborted_shards = 0
        for t, tcfg in enumerate(self.tier_cfgs):
            lo, hi = self._tier_span(t)
            mask_t = np.ascontiguousarray(mask[lo:hi])
            if not mask_t.any():
                z = np.zeros((tcfg.num_symbols,), dtype=np.int64)
                parts.append((z, z, z, z, z, z))
                flags.append(False)
                continue
            with self._snapshot_lock, step_annotation("auction_step",
                                                      self._step_num):
                self.tier_books[t], out = auction_step(
                    tcfg, self.tier_books[t], mask_t)
            dec, fills = decode_auction(tcfg, out)
            flags.append(bool(dec.aborted))
            if dec.aborted:
                aborted_shards += 1
            parts.append((dec.clear_price, dec.executed, dec.best_bid,
                          dec.bid_size, dec.best_ask, dec.ask_size))
            if lo:
                fills = [HostFill(f.sym + lo, f.taker_oid, f.maker_oid,
                                  f.price_q4, f.quantity) for f in fills]
            fills_all.extend(fills)

        cat = [np.concatenate([p[i] for p in parts]) for i in range(6)]
        clear_price, executed, best_bid, bid_size, best_ask, ask_size = cat

        def slot_aborted(slot: int) -> bool:
            return flags[self.tier_of_slot(slot)]

        return (0, clear_price, executed, best_bid, bid_size, best_ask,
                ask_size, fills_all, aborted_shards, slot_aborted)

    def _auction_books_copy(self):
        # Barrier snapshot covers every tier book (self.book is None on
        # tiered runners).
        with self._snapshot_lock:
            return [self._copy_book_tree(b) for b in self.tier_books]

    def _auction_books_restore(self, saved) -> None:
        # Caller holds _snapshot_lock (auction_abort).
        self.tier_books = list(saved)
