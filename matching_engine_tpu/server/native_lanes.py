"""NativeLanesRunner: the C++ lane-engine serving fast path.

The r5 serving ceiling (VERDICT weak #1) was per-OP Python in the bridge
and runner hot loops: ring-record decode, OrderInfo/EngineOp construction,
directory dict mutation, numpy lane scatter, per-result decode, storage
tuple packing, completion building. This runner keeps the EngineRunner's
device machinery (jit'd sparse/dense steps, the pipelined dispatch FIFO,
the dispatch-lock discipline) but moves ALL of that per-op host work into
native/me_lanes.cpp — Python runs per DISPATCH:

    build   -> one ctypes call stages the batch (host checks, id/handle/
               slot assignment, wave placement) straight from the raw
               MeGwOp records.
    wave    -> one ready-to-device_put int32 lane buffer per wave.
    step    -> the unchanged jit'd engine step (sparse [K, 9] or packed
               dense [S, B, 7]).
    decode  -> one ctypes call per wave readback updates the native
               directory and accumulates storage rows + completions.
    finish  -> three buffers out: completions (the gateway batch wire),
               storage (the MeSink wire — fed to the native sink without
               touching Python tuples), and aux (counters, slot/owner
               deltas, stream events) parsed once per dispatch.

Directory ownership: in this mode the C++ engine owns the hot-path order
directory and allocators. Python keeps a symbols<->slot mirror (updated
per dispatch from aux deltas — needed for market-data symbol names and
book snapshots) and syncs the FULL directory only around rare
control-plane mutations (recovery replay, auctions, fill-overflow
reconcile, checkpoint snapshots) via dump_state/adopt. The Python path
(EngineRunner + gateway_bridge._drain_batch) stays the parity oracle:
tests/test_native_lanes.py replays lifecycle-fuzz record streams through
both and asserts identical outcomes, storage rows, and final books.
"""

from __future__ import annotations

import ctypes
from collections import deque

import numpy as np

from matching_engine_tpu import native as me_native
from matching_engine_tpu.engine.book import EngineConfig
from matching_engine_tpu.engine.harness import PIPELINE_DEPTH, run_pipelined
from matching_engine_tpu.engine.kernel import BUY, SELL, fill_inline_count
from matching_engine_tpu.proto import pb2
from matching_engine_tpu.server.engine_runner import EngineRunner, OrderInfo
from matching_engine_tpu.utils.tracing import span, step_annotation


class NativeDispatchResult:
    """One native dispatch's decoded consequences (the DispatchResult twin
    for the record path). Buffers stay wire-format; only the aux sections
    Python must act on are parsed."""

    __slots__ = ("comp_buf", "store_buf", "amends", "local",
                 "order_updates", "market_data", "counters")

    def __init__(self, comp_buf, store_buf, amends, local, order_updates,
                 market_data, counters):
        self.comp_buf = comp_buf            # gateway complete_batch wire
        self.store_buf = store_buf          # MeSink wire
        self.amends = amends                # (tag, ok, remaining, oid, err)
        self.local = local                  # (tag, kind, ok, rem, oid, err)
        self.order_updates = order_updates  # [pb2.OrderUpdate]
        self.market_data = market_data      # [pb2.MarketDataUpdate]
        self.counters = counters


class _NativeStaged:
    """One native dispatch between stage and finish (the _Staged twin).
    `deferred` means every wave's device step is already issued and
    `items` holds the undecoded outputs."""

    __slots__ = ("shape", "arrays", "items", "deferred", "issue", "timeline")

    def __init__(self, shape, arrays, issue, timeline=None):
        self.shape = shape
        self.arrays = arrays  # np lane buffers, one per wave
        self.items = deque()  # issued step outputs awaiting decode
        self.deferred = False
        self.issue = issue    # callable(arr) -> step output
        self.timeline = timeline  # utils/obs.DispatchTimeline | None


def publish_native_result(result: NativeDispatchResult, sink, hub,
                          metrics) -> None:
    """publish_result for the native path: the storage batch ships as the
    already-packed MeSink buffer when the sink supports it (one ctypes
    crossing, no Python tuples); stream events were only materialized when
    subscribers existed."""
    try:
        if sink is not None and len(result.store_buf) > 12:
            if hasattr(sink, "submit_packed"):
                ok = sink.submit_packed(result.store_buf, block=False)
            else:
                orders, updates, fills = me_native.unpack_store_buf(
                    result.store_buf)
                ok = sink.submit(orders=orders, updates=updates, fills=fills,
                                 block=False)
            if not ok:
                metrics.inc("storage_batches_dropped")
        if hub is not None:
            hub.publish_order_updates(result.order_updates)
            hub.publish_market_data(result.market_data)
    except Exception as e:  # noqa: BLE001 — a sink/hub failure must never
        # strand the batch's completions or kill the drain loop. Counter
        # at batch rate, log line rate-limited (see dispatcher twin). The
        # oid span comes from the dispatch's local completions (already
        # parsed — unpacking store_buf on the failure path would do the
        # work the error may stem from); it accumulates across the
        # suppressed window so the printed line bounds the blast radius.
        from matching_engine_tpu.server.dispatcher import _oid_span
        from matching_engine_tpu.utils.obs import warn_rate_limited

        metrics.inc("sink_publish_errors")
        warn_rate_limited(
            "native-lanes-sink",
            f"[native-lanes] sink/hub error: {type(e).__name__}: {e}",
            oid_span=_oid_span([loc[4] or "" for loc in result.local]))


class NativeLanesRunner(EngineRunner):
    """EngineRunner whose serving hot path runs through the C++ lane
    engine. Single-device only (the mesh path amortizes per-op Python
    over much larger dispatches and keeps dense batches)."""

    def __init__(self, cfg: EngineConfig, metrics=None, hub=None,
                 pipeline_inflight: int = 2, oid_offset: int = 0,
                 oid_stride: int = 1, device=None, owns_filter=None,
                 megadispatch_max_waves: int = 1):
        # megadispatch_max_waves > 1: multi-wave DENSE record dispatches
        # stack into native megadispatch — me_lanes.cpp builds ONE
        # [M, S, B, 7] buffer per stack (wave_mega) and decodes the
        # compacted mega readback (decode_mega), so the C++ path's per-
        # wave XLA dispatch cost amortizes exactly like the Python
        # path's _prepare_mega. Bit-identical to M=1 by construction
        # (same engine_step_core scan body; parity pinned by
        # tests/test_batch_edge.py). Sparse dispatches and the Python
        # EngineOp path (boot recovery replay) keep the serial schedule.
        super().__init__(cfg, metrics, mesh=None, hub=hub,
                         pipeline_inflight=pipeline_inflight,
                         oid_offset=oid_offset, oid_stride=oid_stride,
                         device=device, owns_filter=owns_filter,
                         megadispatch_max_waves=megadispatch_max_waves)
        self.lanes = me_native.NativeLanes(
            cfg.num_symbols, cfg.batch, fill_inline_count(cfg), cfg.max_fills)
        if self.oid_stride != 1:
            # The C++ engine owns hot-path OID allocation in this mode;
            # adopt() seeds next_oid onto this lane's residue class and
            # the stride keeps every subsequent allocation on it.
            self.lanes.set_oid_stride(self.oid_stride)
        self.native_lanes = True
        # Until the first adopt, the PYTHON directories are authoritative
        # (boot recovery/restore mutates them directly, engine_runner
        # machinery unchanged); mirror refreshes no-op so a boot-time
        # run_dispatch can't clobber recovered state with the empty
        # native directory. The first record dispatch (or build_server's
        # explicit adopt) flips authority to the C++ engine.
        self._native_authoritative = False

    # -- the native record dispatch ---------------------------------------

    def dispatch_records(self, recs, n: int, on_finish,
                         timeline=None) -> None:
        """Serving-loop entry for raw MeGwOp record batches — the
        dispatch_pipelined twin (same _dispatch_common orchestration).
        `on_finish(result, error)` runs under the dispatch lock when this
        batch decodes (publish there); its return value, if not None,
        runs after release (client completions). `timeline`
        (utils/obs.DispatchTimeline) regains per-stage visibility on
        this path: stamped per DISPATCH, never per op."""

        def stage():
            if not self._native_authoritative:
                # First record dispatch: install whatever boot recovery
                # left in the Python directories (pending FIFO is empty
                # before the first dispatch, so adopt cannot refuse).
                self.adopt_from_python()
            return self._stage_records_locked(recs, n, timeline=timeline)

        self._dispatch_common(stage, on_finish)

    def _stage_records_locked(self, recs, n: int,
                              timeline=None) -> _NativeStaged:
        build_ou = self.hub is None or self.hub.has_order_update_subs()
        build_md = self.hub is None or self.hub.has_market_data_subs()
        # One ctypes crossing stages the whole batch: host checks, oid/
        # handle/slot assignment, wave placement. Raises before any ctx is
        # staged; native registrations are already rolled back on failure.
        with span("lane_build"):
            shape, n_waves, n_lanes, _n_ops, wave_k, wave_n = \
                self.lanes.build(recs, n, build_ou, build_md)
        if shape == 0:
            self.metrics.inc("sparse_dispatches")
        elif n_lanes:
            self.metrics.inc("dense_dispatches")
        # Native megadispatch: a multi-wave dense dispatch stacks into
        # chunks of up to M waves, each one [M', S, B, 7] buffer built in
        # C++ and run through kernel.engine_step_mega's single lax.scan —
        # the same coalescing _prepare_mega gives the Python path. Sparse
        # stays serial (the compacted scan body is dense-shaped).
        m_cap = self.megadispatch_max_waves
        use_mega = shape == 1 and n_waves > 1 and m_cap > 1
        if timeline is not None:
            timeline.shape = ("sparse" if shape == 0
                              else "mega" if use_mega else "dense")
            timeline.waves = n_waves
            if use_mega:
                timeline.mega_m = min(m_cap, n_waves)
        try:
            if use_mega:
                from matching_engine_tpu.engine.kernel import mega_result_cap

                arrays = []
                for w0 in range(0, n_waves, m_cap):
                    m = min(m_cap, n_waves - w0)
                    # The host built the waves, so the deepest wave's real
                    # op count is known exactly: the compacted-completion
                    # bucket can never truncate.
                    rcap = mega_result_cap(self.cfg, max(wave_n[w0:w0 + m]))
                    arrays.append(("mega", m, rcap,
                                   self.lanes.wave_mega(w0, m)))
            else:
                kind = "sparse" if shape == 0 else "dense"
                arrays = [(kind,
                           self.lanes.wave(w, shape, wave_k[w] if shape == 0
                                           else 0))
                          for w in range(n_waves)]
            if timeline is not None:
                timeline.stamp_build()
            staged = _NativeStaged(shape, arrays, self._issue_item,
                                   timeline=timeline)
            if n_waves <= PIPELINE_DEPTH:
                # Dispatch every wave now, decode later — the staged
                # outputs are HBM-bounded by the wave-count cap (a mega
                # item pins the same waves it replaces), and the async
                # host copy lands while the host batches newer work.
                for desc in arrays:
                    item = self._issue_item(desc)
                    staged.items.append(item)
                    try:
                        item[-1].small.copy_to_host_async()
                    except (AttributeError, RuntimeError):
                        pass
                staged.deferred = True
                if timeline is not None:
                    timeline.stamp_issue()
            return staged
        except BaseException:
            # The ctx staged by build() is the NEWEST; drop it (handles/
            # slots stay consumed — the maybe-applied-on-device policy).
            self.lanes.abort(newest=True)
            raise

    def _issue_item(self, desc):
        """Run one staged descriptor's device step; returns the tagged
        (kind, ..., out) item _decode_native consumes FIFO."""
        if desc[0] == "mega":
            _, m, rcap, arr = desc
            from matching_engine_tpu.engine import kernel as _kernel

            self._step_num += 1
            with self._snapshot_lock, step_annotation("engine_step_mega",
                                                      self._step_num):
                self.book, mout = _kernel.engine_step_mega(
                    self.cfg, self.book, arr, rcap)
            self.metrics.inc("megadispatch_steps")
            self.metrics.inc("megadispatch_stacked_waves", m)
            return ("mega", m, rcap, mout)
        if desc[0] == "sparse":
            return ("sparse", self._issue_sparse(desc[1]))
        return ("dense", self._issue_dense(desc[1]))

    def _issue_sparse(self, arr):
        from matching_engine_tpu.engine.sparse import (
            SparseBatch,
            engine_step_sparse,
        )

        self._step_num += 1
        with self._snapshot_lock, step_annotation("engine_step_sparse",
                                                  self._step_num):
            self.book, out = engine_step_sparse(
                self.cfg, self.book, SparseBatch(lanes=arr))
        return out

    def _issue_dense(self, arr):
        from matching_engine_tpu.engine.kernel import engine_step_packed

        self._step_num += 1
        with self._snapshot_lock, step_annotation("engine_step",
                                                  self._step_num):
            self.book, out = engine_step_packed(self.cfg, self.book, arr)
        return out

    def _decode_native(self, item) -> None:
        if item[0] == "mega":
            _, m, rcap, mout = item
            from matching_engine_tpu.engine.kernel import mega_fill_inline

            small = np.asarray(mout.small)
            _fc, fetched = self.lanes.decode_mega(
                m, rcap, mega_fill_inline(self.cfg, rcap), small,
                lambda: np.asarray(mout.fills))
            self.metrics.inc(
                "readback_bytes",
                small.size * 4 + (mout.fills.size * 4 if fetched else 0))
            return
        out = item[1]
        small = np.asarray(out.small)
        fc = self.lanes.decode_wave(small, lambda: np.asarray(out.fills))
        self.metrics.inc(
            "readback_bytes",
            small.size * 4 + (out.fills.size * 4 if fc > self.lanes.L else 0))

    def _finish_locked(self, staged):
        if not isinstance(staged, _NativeStaged):
            return super()._finish_locked(staged)
        try:
            with span("lane_decode"):
                if staged.deferred:
                    while staged.items:
                        self._decode_native(staged.items.popleft())
                else:
                    # Ineligible for deferral (more waves than the
                    # HBM-bounded window): dispatch + decode with the same
                    # bounded dispatch-ahead window as the Python path.
                    def dispatch():
                        for arr in staged.arrays:
                            yield staged.issue(arr)

                    run_pipelined(dispatch(), self._decode_native)
                comp_buf, store_buf, aux_buf = self.lanes.finish_take()
        except BaseException:
            self.lanes.abort(newest=False)
            raise
        aux = me_native.parse_lane_aux(aux_buf)
        result = self._apply_aux_locked(comp_buf, store_buf, aux)
        self.metrics.inc("dispatches")
        self.metrics.inc("engine_ops", aux["counters"].get("engine_ops", 0))
        self.metrics.inc("fills", aux["counters"].get("fill_count", 0))
        self.ops_dispatched += aux["counters"].get("engine_ops", 0)
        if staged.timeline is not None:
            staged.timeline.stamp_decode()
            staged.timeline.counters = dict(aux["counters"])
        return result

    def _apply_aux_locked(self, comp_buf, store_buf, aux) -> NativeDispatchResult:
        c = aux["counters"]
        m = self.metrics
        if c.get("overflow_waves"):
            m.inc("fill_buffer_overflows", c["overflow_waves"])
        for key, metric in (("accepted", "orders_accepted"),
                            ("rejected", "orders_rejected"),
                            ("canceled", "orders_canceled"),
                            ("amended", "orders_amended"),
                            ("owner_overflow", "owner_registry_overflow"),
                            ("owner_collisions", "owner_hash_collisions")):
            if c.get(key):
                m.inc(metric, c[key])
        if c.get("rejected"):
            # Book-capacity backpressure metering on the NATIVE path: the
            # C++ decode already stamps the positional "book side at
            # capacity" reject reason (me_lanes.cpp) — count those here so
            # both serving paths feed the same me_book_* series. Both
            # completion routes are covered: bit-63 tags (grpcio lane
            # ring) ride aux["local"], gateway-batch tags ride the comp
            # wire buffer. The gate is effective: the C++ `rejected`
            # counter covers edge rejects + device SUBMIT rejects only —
            # cancel-of-filled rejects (the common structural class,
            # ~13% of ops in crash replays) never bump it — so the extra
            # comp parse runs on genuinely rare dispatches, never per op
            # on the clean hot path.
            for loc in aux["local"]:
                if "book side at capacity" in loc[5]:
                    self._meter_capacity_reject(0)
            for comp in me_native.parse_comp_buf(comp_buf):
                if "book side at capacity" in comp[4]:
                    self._meter_capacity_reject(0)
        # Slot mirror deltas FIRST (market data below resolves symbol
        # names through the mirror), releases LAST (the Python finalize
        # also publishes before eviction recycles slots).
        for slot, sym in aux["slot_allocs"]:
            self.symbols[sym] = slot
            self.slot_symbols[slot] = sym
        for cid, owner in aux["new_owners"]:
            self._owner_by_client[cid] = owner
            self._owner_claimed[owner] = cid
            self.pending_owner_ids.append((cid, owner))
            m.inc("owner_ids_assigned")
        for oid, qty in aux["recon"]:
            self._ledger_lost(oid, qty)
        market_data = []
        for slot, bb, bs, ba, asz in aux["market_data"]:
            sym = self.slot_symbols[slot]
            if sym is None:
                continue
            market_data.append(pb2.MarketDataUpdate(
                symbol=sym, best_bid=bb, best_ask=ba, scale=4,
                bid_size=bs, ask_size=asz))
        for slot in aux["slot_releases"]:
            sym = self.slot_symbols[slot]
            if sym is not None:
                del self.symbols[sym]
                self.slot_symbols[slot] = None
        order_updates = [
            pb2.OrderUpdate(
                order_id=oid, client_id=cid, symbol=sym, status=status,
                fill_price=fprice, scale=4, fill_quantity=fqty,
                remaining_quantity=rem)
            for (status, fprice, fqty, rem, oid, cid, sym)
            in aux["order_updates"]
        ]
        return NativeDispatchResult(comp_buf, store_buf, aux["amends"],
                                    aux["local"], order_updates, market_data,
                                    c)

    # -- directory sync with the Python mirror -----------------------------
    #
    # Rare control-plane mutations (recovery replay, auctions, overflow
    # reconcile) run the ORACLE Python machinery over a freshly-synced
    # mirror, then install the result back natively. Hot-path state never
    # crosses per op. Callers hold the dispatch lock with the pending FIFO
    # drained (adopt refuses otherwise).

    def sync_directory_for_snapshot_locked(self) -> None:
        self.refresh_directory_mirror_locked()

    def refresh_directory_mirror_locked(self) -> None:
        if not self._native_authoritative:
            return  # Python state is still authoritative (pre-adopt boot)
        st = me_native.parse_lane_state(self.lanes.dump_state())
        cfg = self.cfg
        self.next_oid_num = st["next_oid"]
        self._next_handle = st["next_handle"]
        self._free_handles = list(st["free_handles"])
        self._next_slot = st["next_slot"]
        self._free_slots = list(st["free_slots"])
        self.symbols = {}
        self.slot_symbols = [None] * cfg.num_symbols
        self._slot_live = [0] * cfg.num_symbols
        for slot, live, sym in st["symbols"]:
            self.symbols[sym] = slot
            self.slot_symbols[slot] = sym
            self._slot_live[slot] = live
        self._owner_by_client = {cid: o for cid, o in st["owners"]}
        self._owner_claimed = {o: cid for cid, o in st["owners"]}
        self.orders_by_handle = {}
        self.orders_by_id = {}
        for (handle, oid, cid, sym, side, otype, price, qty, rem,
             status) in st["orders"]:
            info = OrderInfo(
                oid=oid, order_id=f"OID-{oid}", client_id=cid, symbol=sym,
                side=side, otype=otype, price_q4=price, quantity=qty,
                remaining=rem, status=status, handle=handle)
            self.orders_by_handle[handle] = info
            self.orders_by_id[info.order_id] = info
        self.auction_mode = st["auction_mode"]

    def adopt_from_python(self) -> None:
        """Install the Python directories/allocators as the native state
        (after boot recovery/restore or a Python-path mutation)."""
        blob = me_native.pack_lane_state(
            next_oid=self.next_oid_num,
            next_handle=self._next_handle,
            free_handles=self._free_handles,
            next_slot=self._next_slot,
            free_slots=self._free_slots,
            symbols=[(slot, self._slot_live[slot], sym)
                     for sym, slot in sorted(self.symbols.items(),
                                             key=lambda kv: kv[1])],
            owners=list(self._owner_by_client.items()),
            orders=[(i.handle, i.oid, i.client_id, i.symbol, i.side,
                     i.otype, i.price_q4, i.quantity, i.remaining, i.status)
                    for i in self.orders_by_handle.values()],
            auction_mode=self.auction_mode,
        )
        self.lanes.adopt(blob)
        self._native_authoritative = True

    # Python-path mutating entry points: sync around them so the oracle
    # machinery (recovery, auctions, reconcile) stays exactly as-is.

    def _run_dispatch_locked(self, ops):
        self.refresh_directory_mirror_locked()
        try:
            return super()._run_dispatch_locked(ops)
        finally:
            self.adopt_from_python()

    def _run_auction_locked(self, symbols, sink):
        self.refresh_directory_mirror_locked()
        try:
            return super()._run_auction_locked(symbols, sink)
        finally:
            self.adopt_from_python()

    # Cross-lane barrier hooks (run_auction_phased): prepare imports the
    # native directory state into the python mirror exactly like the
    # single-lane auction entry; commit/abort push the (mutated or
    # untouched) mirror back so the native directory never desyncs, on
    # either barrier outcome.

    def auction_prepare(self, symbols):
        self.refresh_directory_mirror_locked()
        return super().auction_prepare(symbols)

    def auction_commit(self, prep, sink=None):
        try:
            return super().auction_commit(prep, sink)
        finally:
            self.adopt_from_python()

    def auction_abort(self, prep) -> None:
        try:
            super().auction_abort(prep)
        finally:
            self.adopt_from_python()

    def reconcile_fill_overflow(self):
        self.refresh_directory_mirror_locked()
        try:
            return super().reconcile_fill_overflow()
        finally:
            self.adopt_from_python()

    def dispatch_pipelined(self, ops, on_finish, timeline=None) -> None:
        raise NotImplementedError(
            "NativeLanesRunner serves through dispatch_records; the "
            "EngineOp path would desync the native directory (use "
            "run_dispatch for boot-time replay)")

    def set_auction_mode(self, value: bool) -> None:
        super().set_auction_mode(value)
        self.lanes.set_auction_mode(value)

    # -- read-only views over the native directory -------------------------

    def native_order(self, order_id: str) -> OrderInfo | None:
        """Directory lookup against the native hot-path state."""
        handle = self.lanes.lookup(order_id)
        if not handle:
            return None
        rec = self.lanes.get_order(handle)
        if rec is None:
            return None
        (oid, side, otype, price_q4, status, qty, rem, sym, cid) = rec
        return OrderInfo(oid=oid, order_id=f"OID-{oid}", client_id=cid,
                         symbol=sym, side=side, otype=otype,
                         price_q4=price_q4, quantity=qty, remaining=rem,
                         status=status, handle=handle)

    def book_snapshot(self, symbol: str):
        """Parent's snapshot with the directory join served natively."""
        slot = self.symbols.get(symbol)
        if slot is None:
            return [], []
        bp, bq, bo, bs_, ap, aq, ao, as_ = self._snapshot_row(slot)

        def side(price, qty, oid, seq, desc, want_side):
            rows = [
                (int(oid[j]), int(price[j]), int(qty[j]), int(seq[j]))
                for j in np.nonzero(qty > 0)[0]
            ]
            rows.sort(key=lambda r: (-r[1] if desc else r[1], r[3]))
            out = []
            for h, p, q, _ in rows:
                rec = self.lanes.get_order(h)
                if rec is None:
                    continue
                (oid_n, side_, otype, price_q4, status, qty_, rem,
                 sym, cid) = rec
                # Same recycled-handle consistency guard as the parent.
                if sym == symbol and side_ == want_side and price_q4 == p:
                    out.append((OrderInfo(
                        oid=oid_n, order_id=f"OID-{oid_n}", client_id=cid,
                        symbol=sym, side=side_, otype=otype,
                        price_q4=price_q4, quantity=qty_, remaining=rem,
                        status=status, handle=h), q))
            return out

        return (side(bp, bq, bo, bs_, True, BUY),
                side(ap, aq, ao, as_, False, SELL))


def pack_record_batch(records) -> tuple:
    """Pack an iterable of record tuples into an (MeGwOp * n) array.

    records: (tag, op, side, otype, price_q4, quantity, symbol, client_id,
    order_id) with str or bytes strings — the pop_batch tuple order.
    Benches and tests pre-pack streams with this; the serving edges pop
    raw buffers and never touch it."""
    recs = list(records)
    arr = (me_native.MeGwOp * max(1, len(recs)))()
    for i, (tag, op, side, otype, price, qty, sym, cid, oid) in \
            enumerate(recs):
        me_native.pack_gwop(
            arr[i], tag, op, side=side, otype=otype, price_q4=price,
            quantity=qty,
            symbol=sym.encode() if isinstance(sym, str) else sym,
            client_id=cid.encode() if isinstance(cid, str) else cid,
            order_id=oid.encode() if isinstance(oid, str) else oid)
    return arr, len(recs)


def snapshot_records(buf, n: int):
    """Copy the first n records out of a reused pop buffer (one memmove,
    not per-op Python) — the error path's completion source and the
    pipelined dispatch's stable reference."""
    snap = (me_native.MeGwOp * max(1, n))()
    ctypes.memmove(snap, buf, ctypes.sizeof(me_native.MeGwOp) * n)
    return snap
