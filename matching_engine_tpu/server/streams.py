"""Fan-out hubs for the two streaming RPCs, with the sequenced feed.

The reference declares StreamMarketData and StreamOrderUpdates but never
overrides them — clients get UNIMPLEMENTED (SURVEY.md §3.4). Here they are
real: the dispatcher publishes each dispatch's market-data and order-update
events into per-subscriber bounded queues; stream handlers drain their queue
until the client hangs up. Slow consumers lose oldest events (bounded queue,
drop-oldest) rather than stalling the engine — but since the feed layer
landed that loss is *accounted* (stream_dropped_events) and *recoverable*:

- With a `FeedSequencer` attached (feed/sequencer.py; build_server wires it
  unless --feed-depth 0), publish_* stamps every event with its
  per-(channel, key) monotonic `seq` and retains it in the retransmission
  store BEFORE fan-out, so any dropped event can be replayed via
  `resume_from_seq` (service.py) and every gap is client-detectable.
- A sequenced hub reports has_*_subs() = True so both serving paths
  materialize events even with no live subscriber — the store must cover
  a reconnecting client's away window.
- `subscribe_market_data(conflate=True)` returns a conflated latest-state
  channel: a slow L2 consumer sees the newest snapshot instead of a
  backlog (feed_conflated_events counts the skipped states).

Delivery is event-driven end to end: queue.Queue wakes a blocked get() from
put() via its condition variable (sub-ms publish->yield, pinned by
tests/test_metrics.py::test_stream_latency_metric_and_wakeup), and stream
termination rides the gRPC context callback (service.py add_callback ->
unsubscribe -> sentinel) rather than an aliveness poll — an idle subscriber
thread sleeps in get() indefinitely instead of waking 4x/s. The optional
`alive` polling path remains for callers without a termination callback.

Every published event is stamped at offer() and measured at yield:
stream_latency_us_p50/_p99 in GetMetrics is the publish->yield figure.
"""

from __future__ import annotations

import queue
import threading
import time

from matching_engine_tpu.feed.sequencer import (
    AUDIT_DOMAIN_KEY,
    CHANNEL_AUDIT,
    CHANNEL_MD,
    CHANNEL_OU,
    CHANNEL_OPLOG,
    OPLOG_DISPATCH,
    OPLOG_DOMAIN_KEY,
)
from matching_engine_tpu.proto import pb2

_SENTINEL = object()


class _Subscription:
    def __init__(self, maxsize: int, metrics=None):
        self.q: queue.Queue = queue.Queue(maxsize=maxsize)
        self._metrics = metrics
        # Highest seq yielded to this consumer (sequenced hubs); seeded
        # with the domain head at subscribe so the lag gauge measures
        # backlog since attach, not since the shard booted.
        self.last_seq = 0
        self.drops = 0

    def offer(self, item) -> None:
        entry = (time.perf_counter(), item)
        while True:
            try:
                self.q.put_nowait(entry)
                return
            except queue.Full:
                try:
                    _, dropped = self.q.get_nowait()  # drop oldest
                except queue.Empty:
                    continue
                if dropped is not _SENTINEL:
                    # The previously-invisible loss mode, now a counter:
                    # a sequenced client recovers the dropped range via
                    # resume_from_seq; a legacy client at least sees the
                    # loss in GetMetrics / me_stream_dropped_events_total.
                    self.drops += 1
                    if self._metrics is not None:
                        self._metrics.inc("stream_dropped_events")

    def stream(self, alive=None):
        """Yield events until closed.

        With `alive=None` (the gRPC path) the generator blocks in get()
        until an event or the close() sentinel arrives — termination is
        the service layer's context callback calling unsubscribe(). A
        callable `alive` is polled every 0.25s instead, for callers with
        no termination hook."""
        while alive is None or alive():
            try:
                t_pub, item = self.q.get(
                    timeout=None if alive is None else 0.25)
            except queue.Empty:
                continue
            if item is _SENTINEL:
                return
            if self._metrics is not None:
                self._metrics.observe(
                    "stream_latency_us", (time.perf_counter() - t_pub) * 1e6)
            seq = getattr(item, "seq", 0)
            if seq:
                self.last_seq = seq
            yield item

    def close(self) -> None:
        self.offer(_SENTINEL)


class _ConflatedSubscription(_Subscription):
    """Latest-state channel for slow consumers (MarketDataRequest.conflate):
    instead of queueing a backlog and dropping its oldest tail, overflow
    replaces the *pending* states with the newest — the consumer always
    converges on the current book, skipping intermediates by contract.
    maxsize 2 = one state possibly mid-read + the newest."""

    def __init__(self, metrics=None):
        super().__init__(maxsize=2, metrics=metrics)

    def offer(self, item) -> None:
        entry = (time.perf_counter(), item)
        while True:
            try:
                self.q.put_nowait(entry)
                return
            except queue.Full:
                try:
                    _, old = self.q.get_nowait()
                except queue.Empty:
                    continue
                if old is not _SENTINEL and self._metrics is not None:
                    # Conflation, not loss: the skipped state is obsolete
                    # by definition and the client asked for latest-only.
                    self._metrics.inc("feed_conflated_events")


class StreamHub:
    def __init__(self, maxsize: int = 1024, metrics=None, sequencer=None):
        self._lock = threading.Lock()
        self._maxsize = maxsize
        self._metrics = metrics
        self.sequencer = sequencer  # feed.FeedSequencer | None
        self._md_subs: dict[str, list[_Subscription]] = {}      # symbol ->
        self._ou_subs: dict[str, list[_Subscription]] = {}      # client_id ->
        self._audit_subs: list[_Subscription] = []              # drop-copy
        self._oplog_subs: list[_Subscription] = []              # replication

    # -- subscription management ------------------------------------------

    def has_market_data_subs(self) -> bool:
        """Lock-free peek: the decode path skips BUILDING MarketDataUpdate
        protos entirely when nobody is listening (the common serving case)
        — unless the sequenced feed is on, whose retransmission store must
        cover windows with no live subscriber (a reconnecting client
        replays them). A subscriber attaching mid-dispatch just misses
        that dispatch — same semantics as attaching a moment later."""
        return self.sequencer is not None or bool(self._md_subs)

    def has_order_update_subs(self) -> bool:
        return self.sequencer is not None or bool(self._ou_subs)

    def subscribe_market_data(self, symbol: str,
                              conflate: bool = False) -> _Subscription:
        if conflate:
            sub = _ConflatedSubscription(self._metrics)
        else:
            sub = _Subscription(self._maxsize, self._metrics)
        if self.sequencer is not None:
            sub.last_seq = self.sequencer.last_seq(CHANNEL_MD, symbol)
        with self._lock:
            self._md_subs.setdefault(symbol, []).append(sub)
        return sub

    def subscribe_order_updates(self, client_id: str) -> _Subscription:
        sub = _Subscription(self._maxsize, self._metrics)
        if self.sequencer is not None:
            sub.last_seq = self.sequencer.last_seq(CHANNEL_OU, client_id)
        with self._lock:
            self._ou_subs.setdefault(client_id, []).append(sub)
        return sub

    def subscribe_audit(self) -> _Subscription:
        """Attach to the drop-copy audit channel (every lifecycle record
        from every symbol/client — the venue-wide surveillance tap)."""
        sub = _Subscription(self._maxsize, self._metrics)
        if self.sequencer is not None:
            sub.last_seq = self.sequencer.last_seq(CHANNEL_AUDIT,
                                                   AUDIT_DOMAIN_KEY)
        with self._lock:
            self._audit_subs.append(sub)
        return sub

    def subscribe_oplog(self) -> _Subscription:
        """Attach to the replication op-log channel (every admitted
        dispatch's op records + heartbeats — the warm-standby input)."""
        sub = _Subscription(self._maxsize, self._metrics)
        if self.sequencer is not None:
            sub.last_seq = self.sequencer.last_seq(CHANNEL_OPLOG,
                                                   OPLOG_DOMAIN_KEY)
        with self._lock:
            self._oplog_subs.append(sub)
        return sub

    def unsubscribe(self, sub: _Subscription) -> None:
        with self._lock:
            for table in (self._md_subs, self._ou_subs):
                for key, subs in list(table.items()):
                    if sub in subs:
                        subs.remove(sub)
                        if not subs:
                            del table[key]
            if sub in self._audit_subs:
                self._audit_subs.remove(sub)
            if sub in self._oplog_subs:
                self._oplog_subs.remove(sub)
        sub.close()

    # -- publication (called from the dispatcher thread) -------------------

    def publish_market_data(self, updates: list[pb2.MarketDataUpdate]) -> None:
        if not updates:
            return
        with self._lock:
            if self.sequencer is not None:
                # Stamp + retain BEFORE fan-out: an event is replayable
                # the instant any subscriber could have seen (or dropped)
                # it. Stamping happens INSIDE the hub lock so stamp and
                # fan-out are atomic across publishers: with K serving
                # lanes publishing concurrently (server/shards.py), a
                # later-stamped batch must not reach a subscriber queue
                # before an earlier-stamped one for the same key — the
                # inversion would read as a gap and trigger spurious
                # gap-fills (tests/test_serve_shards.py pins delivery
                # order). The sequencer lock nests inside; nothing takes
                # them in the other order.
                self.sequencer.stamp_market_data(updates)
            for u in updates:
                for sub in self._md_subs.get(u.symbol, ()):
                    sub.offer(u)
            self._update_lag_locked(CHANNEL_MD,
                                    {u.symbol for u in updates})

    def publish_order_updates(self, updates: list[pb2.OrderUpdate]) -> None:
        if not updates:
            return
        with self._lock:
            if self.sequencer is not None:
                # Same stamp/fan-out atomicity as publish_market_data.
                self.sequencer.stamp_order_updates(updates)
            for u in updates:
                for sub in self._ou_subs.get(u.client_id, ()):
                    sub.offer(u)
            self._update_lag_locked(CHANNEL_OU,
                                    {u.client_id for u in updates})

    def publish_oplog(self, updates: list[pb2.OrderUpdate]) -> None:
        """Stamp + fan out op-log events (replication/oplog.py builds the
        protos OUTSIDE this call — nothing materializes under the hub
        lock). Same stamp/fan-out atomicity as the other publish_* paths:
        with K serving lanes shipping concurrently, the venue-wide oplog
        seq line interleaves dispatches in stamp order and a standby
        applies exactly that order. Only DISPATCH events are stamped and
        retained: heartbeats (4/s, forever) fan out live with seq 0 —
        sequencing them would evict real dispatches from the standby's
        catch-up window and make a long idle disconnect read as
        unrecoverable loss when nothing but liveness pings were missed."""
        if not updates:
            return
        stamped = [u for u in updates if u.oplog_kind == OPLOG_DISPATCH]
        with self._lock:
            if self.sequencer is not None and stamped:
                self.sequencer.stamp_oplog(stamped)
            for u in updates:
                for sub in self._oplog_subs:
                    sub.offer(u)

    def publish_audit_rows(self, rows, env, n: int, drop=None,
                           observer=None) -> list[int]:
        """Stamp + (when tapped) fan out one dispatch's drop-copy rows.
        Same stamp/fan-out atomicity as the other publish_* paths (the
        audit seq line interleaves every serving lane's dispatches in
        stamp order) — but the retained form is the ROW CHUNK, not
        per-record protos: wire events materialize only for live
        subscribers here and for replay in the sequencer
        (copy-on-replay), so the subscriber-less steady state pays no
        per-record proto work on the publish path.

        `drop` (a flat record index) is the fault-injection seam: the
        record is STAMPED/retained but not delivered — exactly the
        "event lost between decode and publish" corruption the
        auditor's seq-continuity invariant exists to catch.
        `observer(seqs)` runs INSIDE the hub lock with the delivered
        seq list: the in-process auditor must consume batches in stamp
        order, and with K serving lanes publishing concurrently an
        out-of-lock feed would interleave (reading as spurious seq
        gaps). The auditor's own lock nests inside the hub lock, same
        as the sequencer's. Returns the delivered seqs (all zero when
        the feed is disabled)."""
        if n == 0:
            if observer is not None:
                with self._lock:
                    observer([])
            return []
        with self._lock:
            if self.sequencer is not None:
                first = self.sequencer.stamp_audit_rows(rows, env, n)
                seqs = [first + i for i in range(n) if i != drop]
            else:
                first = 0
                seqs = [0] * (n - (1 if drop is not None else 0))
            if self._audit_subs:
                from matching_engine_tpu.audit.dropcopy import (
                    materialize_chunk,
                )

                events = materialize_chunk(
                    rows, env, first,
                    self.sequencer.epoch if self.sequencer else 0,
                    skip=drop)
                for e in events:
                    for sub in self._audit_subs:
                        sub.offer(e)
            if observer is not None:
                observer(seqs)
        return seqs

    def _update_lag_locked(self, channel: str, keys) -> None:
        """feed_subscriber_lag_max: worst (domain head − last yielded seq)
        across subscribers of the keys THIS batch touched — the
        backpressure signal that says WHICH side is slow before drops/
        conflation start. Scanning every subscribed key here (under the
        hub lock, per publish batch — the path every serving lane
        serializes through) would grow per-dispatch cost with subscriber
        count; an untouched key's head is static, so its lag can only
        shrink while it goes unsampled — the gauge stays a faithful
        worst-case at its next publish."""
        if self.sequencer is None or self._metrics is None:
            return
        table = self._md_subs if channel == CHANNEL_MD else self._ou_subs
        lag = 0
        for key in keys:
            subs = table.get(key)
            if not subs:
                continue
            head = self.sequencer.last_seq(channel, key)
            for s in subs:
                lag = max(lag, head - s.last_seq)
        self._metrics.set_gauge("feed_subscriber_lag_max", lag)

    def close_all(self) -> None:
        with self._lock:
            subs = [s for v in self._md_subs.values() for s in v]
            subs += [s for v in self._ou_subs.values() for s in v]
            subs += list(self._audit_subs)
            subs += list(self._oplog_subs)
            self._md_subs.clear()
            self._ou_subs.clear()
            self._audit_subs.clear()
            self._oplog_subs.clear()
        for s in subs:
            s.close()
