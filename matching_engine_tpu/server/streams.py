"""Fan-out hubs for the two streaming RPCs.

The reference declares StreamMarketData and StreamOrderUpdates but never
overrides them — clients get UNIMPLEMENTED (SURVEY.md §3.4). Here they are
real: the dispatcher publishes each dispatch's market-data and order-update
events into per-subscriber bounded queues; stream handlers drain their queue
until the client hangs up. Slow consumers lose oldest events (bounded queue,
drop-oldest) rather than stalling the engine.
"""

from __future__ import annotations

import queue
import threading

from matching_engine_tpu.proto import pb2

_SENTINEL = object()


class _Subscription:
    def __init__(self, maxsize: int):
        self.q: queue.Queue = queue.Queue(maxsize=maxsize)

    def offer(self, item) -> None:
        while True:
            try:
                self.q.put_nowait(item)
                return
            except queue.Full:
                try:
                    self.q.get_nowait()  # drop oldest
                except queue.Empty:
                    pass

    def stream(self, alive=lambda: True):
        """Yield events until closed; `alive` is polled between events."""
        while alive():
            try:
                item = self.q.get(timeout=0.25)
            except queue.Empty:
                continue
            if item is _SENTINEL:
                return
            yield item

    def close(self) -> None:
        self.offer(_SENTINEL)


class StreamHub:
    def __init__(self, maxsize: int = 1024):
        self._lock = threading.Lock()
        self._maxsize = maxsize
        self._md_subs: dict[str, list[_Subscription]] = {}      # symbol ->
        self._ou_subs: dict[str, list[_Subscription]] = {}      # client_id ->

    # -- subscription management ------------------------------------------

    def has_market_data_subs(self) -> bool:
        """Lock-free peek: the decode path skips BUILDING MarketDataUpdate
        protos entirely when nobody is listening (the common serving case).
        A subscriber attaching mid-dispatch just misses that dispatch —
        same semantics as attaching a moment later."""
        return bool(self._md_subs)

    def has_order_update_subs(self) -> bool:
        return bool(self._ou_subs)

    def subscribe_market_data(self, symbol: str) -> _Subscription:
        sub = _Subscription(self._maxsize)
        with self._lock:
            self._md_subs.setdefault(symbol, []).append(sub)
        return sub

    def subscribe_order_updates(self, client_id: str) -> _Subscription:
        sub = _Subscription(self._maxsize)
        with self._lock:
            self._ou_subs.setdefault(client_id, []).append(sub)
        return sub

    def unsubscribe(self, sub: _Subscription) -> None:
        with self._lock:
            for table in (self._md_subs, self._ou_subs):
                for key, subs in list(table.items()):
                    if sub in subs:
                        subs.remove(sub)
                        if not subs:
                            del table[key]
        sub.close()

    # -- publication (called from the dispatcher thread) -------------------

    def publish_market_data(self, updates: list[pb2.MarketDataUpdate]) -> None:
        if not updates:
            return
        with self._lock:
            for u in updates:
                for sub in self._md_subs.get(u.symbol, ()):
                    sub.offer(u)

    def publish_order_updates(self, updates: list[pb2.OrderUpdate]) -> None:
        if not updates:
            return
        with self._lock:
            for u in updates:
                for sub in self._ou_subs.get(u.client_id, ()):
                    sub.offer(u)

    def close_all(self) -> None:
        with self._lock:
            subs = [s for v in self._md_subs.values() for s in v]
            subs += [s for v in self._ou_subs.values() for s in v]
            self._md_subs.clear()
            self._ou_subs.clear()
        for s in subs:
            s.close()
