"""Fan-out hubs for the two streaming RPCs.

The reference declares StreamMarketData and StreamOrderUpdates but never
overrides them — clients get UNIMPLEMENTED (SURVEY.md §3.4). Here they are
real: the dispatcher publishes each dispatch's market-data and order-update
events into per-subscriber bounded queues; stream handlers drain their queue
until the client hangs up. Slow consumers lose oldest events (bounded queue,
drop-oldest) rather than stalling the engine.

Delivery is event-driven end to end: queue.Queue wakes a blocked get() from
put() via its condition variable (sub-ms publish->yield, pinned by
tests/test_metrics.py::test_stream_latency_metric_and_wakeup), and stream
termination rides the gRPC context callback (service.py add_callback ->
unsubscribe -> sentinel) rather than an aliveness poll — an idle subscriber
thread sleeps in get() indefinitely instead of waking 4x/s. The optional
`alive` polling path remains for callers without a termination callback.

Every published event is stamped at offer() and measured at yield:
stream_latency_us_p50/_p99 in GetMetrics is the publish->yield figure.
"""

from __future__ import annotations

import queue
import threading
import time

from matching_engine_tpu.proto import pb2

_SENTINEL = object()


class _Subscription:
    def __init__(self, maxsize: int, metrics=None):
        self.q: queue.Queue = queue.Queue(maxsize=maxsize)
        self._metrics = metrics

    def offer(self, item) -> None:
        entry = (time.perf_counter(), item)
        while True:
            try:
                self.q.put_nowait(entry)
                return
            except queue.Full:
                try:
                    self.q.get_nowait()  # drop oldest
                except queue.Empty:
                    pass

    def stream(self, alive=None):
        """Yield events until closed.

        With `alive=None` (the gRPC path) the generator blocks in get()
        until an event or the close() sentinel arrives — termination is
        the service layer's context callback calling unsubscribe(). A
        callable `alive` is polled every 0.25s instead, for callers with
        no termination hook."""
        while alive is None or alive():
            try:
                t_pub, item = self.q.get(
                    timeout=None if alive is None else 0.25)
            except queue.Empty:
                continue
            if item is _SENTINEL:
                return
            if self._metrics is not None:
                self._metrics.observe(
                    "stream_latency_us", (time.perf_counter() - t_pub) * 1e6)
            yield item

    def close(self) -> None:
        self.offer(_SENTINEL)


class StreamHub:
    def __init__(self, maxsize: int = 1024, metrics=None):
        self._lock = threading.Lock()
        self._maxsize = maxsize
        self._metrics = metrics
        self._md_subs: dict[str, list[_Subscription]] = {}      # symbol ->
        self._ou_subs: dict[str, list[_Subscription]] = {}      # client_id ->

    # -- subscription management ------------------------------------------

    def has_market_data_subs(self) -> bool:
        """Lock-free peek: the decode path skips BUILDING MarketDataUpdate
        protos entirely when nobody is listening (the common serving case).
        A subscriber attaching mid-dispatch just misses that dispatch —
        same semantics as attaching a moment later."""
        return bool(self._md_subs)

    def has_order_update_subs(self) -> bool:
        return bool(self._ou_subs)

    def subscribe_market_data(self, symbol: str) -> _Subscription:
        sub = _Subscription(self._maxsize, self._metrics)
        with self._lock:
            self._md_subs.setdefault(symbol, []).append(sub)
        return sub

    def subscribe_order_updates(self, client_id: str) -> _Subscription:
        sub = _Subscription(self._maxsize, self._metrics)
        with self._lock:
            self._ou_subs.setdefault(client_id, []).append(sub)
        return sub

    def unsubscribe(self, sub: _Subscription) -> None:
        with self._lock:
            for table in (self._md_subs, self._ou_subs):
                for key, subs in list(table.items()):
                    if sub in subs:
                        subs.remove(sub)
                        if not subs:
                            del table[key]
        sub.close()

    # -- publication (called from the dispatcher thread) -------------------

    def publish_market_data(self, updates: list[pb2.MarketDataUpdate]) -> None:
        if not updates:
            return
        with self._lock:
            for u in updates:
                for sub in self._md_subs.get(u.symbol, ()):
                    sub.offer(u)

    def publish_order_updates(self, updates: list[pb2.OrderUpdate]) -> None:
        if not updates:
            return
        with self._lock:
            for u in updates:
                for sub in self._ou_subs.get(u.client_id, ()):
                    sub.offer(u)

    def close_all(self) -> None:
        with self._lock:
            subs = [s for v in self._md_subs.values() for s in v]
            subs += [s for v in self._ou_subs.values() for s in v]
            self._md_subs.clear()
            self._ou_subs.clear()
        for s in subs:
            s.close()
