"""BatchDispatcher: the host-side throughput/latency knob.

The north-star architecture (BASELINE.json): the gRPC handlers don't touch
the device — they enqueue validated ops and wait on a per-op future. One
dispatcher thread drains the queue on a time/size trigger (whichever comes
first), ships a dense dispatch through the EngineRunner, completes futures,
hands storage events to the async sink, and fans stream events out to the
hubs. This replaces the reference's global `write_mu` serialization point
(matching_engine_service.cpp:102) with pipelined batches: RPC threads block
only on their own op's completion, and a whole batch costs one kernel launch.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from collections import namedtuple
from concurrent.futures import Future

from matching_engine_tpu.server.engine_runner import EngineOp, EngineRunner
from matching_engine_tpu.utils.metrics import Metrics
from matching_engine_tpu.utils.obs import (
    DispatchTimeline,
    record_dispatch_error,
    warn_rate_limited,
)


class RingFull(RuntimeError):
    """Op rejected before entering the dispatch queue (native ring full).

    Distinct from generic dispatch failures because the op is KNOWN to have
    never been enqueued: the caller may safely recycle the op's handle/slot
    (EngineRunner.release_unqueued) — for a maybe-enqueued failure that
    would risk handle reuse against a possibly-live order."""


def spin_get(q: queue.Queue, timeout_s: float | None, spin_s: float):
    """queue.Queue.get with a bounded busy-poll before the condvar wait.

    The --busy-poll-us tail lever: a condvar wakeup (producer put ->
    consumer scheduled) costs tens of microseconds of scheduler latency
    per drain cycle, which lands squarely in the queue-wait stage's tail.
    Spinning get_nowait for up to `spin_s` catches an op arriving within
    the spin window with no syscall; past it, the normal blocking get
    takes over (deadline preserved), so semantics — and serving output —
    are bit-identical to spin_s=0. Raises queue.Empty exactly like get().
    """
    if spin_s > 0.0:
        t0 = time.perf_counter()
        spin_deadline = t0 + (spin_s if timeout_s is None
                              else min(spin_s, timeout_s))
        while time.perf_counter() < spin_deadline:
            try:
                return q.get_nowait()
            except queue.Empty:
                pass
        if timeout_s is not None:
            timeout_s = max(0.0, t0 + timeout_s - time.perf_counter())
    return q.get(timeout=timeout_s)


def spin_result(fut: Future, timeout_s: float, spin_s: float):
    """Future.result with a bounded busy-poll before the condvar wait —
    the completion side of --busy-poll-us (the RPC thread's wakeup after
    its op's dispatch decodes is the other condvar round trip on the
    submit path). Identical result semantics to fut.result(timeout)."""
    if spin_s > 0.0:
        deadline = time.perf_counter() + spin_s
        while time.perf_counter() < deadline:
            if fut.done():
                return fut.result(timeout=0)
    return fut.result(timeout=timeout_s)


def _oid_span(order_ids) -> tuple[int, int] | None:
    """(lo, hi) numeric order-id range over an id iterable — the failure
    paths stamp WHICH orders a suppressed sink/hub error window touched,
    so a post-mortem can bound the blast radius. Error-path only; never
    on the hot path."""
    lo = hi = None
    for oid in order_ids:
        if not oid or not oid.startswith("OID-"):
            continue
        try:
            n = int(oid[4:])
        except ValueError:
            continue
        lo = n if lo is None else min(lo, n)
        hi = n if hi is None else max(hi, n)
    return None if lo is None else (lo, hi)


def publish_result(result, sink, hub, metrics) -> None:
    """Enqueue one dispatch's storage/stream events. Shared by every drain
    loop (BatchDispatcher and GatewayBridge): a sink/hub failure must never
    strand the batch's completions or kill the loop — the match result
    already exists in the book."""
    try:
        if sink is not None:
            # Non-blocking: a stalled SQLite must not backpressure the
            # match loop (we prefer losing durable-log tail to stalling
            # matching; the sink counts drops and the book checkpoint
            # reconciles).
            if not sink.submit(
                orders=result.storage_orders,
                updates=result.storage_updates,
                fills=result.storage_fills,
                block=False,
            ):
                metrics.inc("storage_batches_dropped")
        if hub is not None:
            hub.publish_order_updates(result.order_updates)
            hub.publish_market_data(result.market_data)
    except Exception as e:  # noqa: BLE001
        # Counted at batch rate (me_sink_publish_errors_total is the alert
        # signal); logged at human rate — a flapping sink fails every
        # drain and must not spam stdout at batch frequency. The oid span
        # accumulates across the suppressed window.
        metrics.inc("sink_publish_errors")
        warn_rate_limited(
            "dispatcher-sink",
            f"[dispatcher] sink/hub error: {type(e).__name__}: {e}",
            oid_span=_oid_span(
                [r[0] for r in result.storage_orders]
                + [r[0] for r in result.storage_updates]))


class BatchDispatcher:
    # Flight-recorder/ledger label for dispatches drained by this edge.
    timeline_path = "python"

    def __init__(
        self,
        runner: EngineRunner,
        sink=None,          # AsyncStorageSink | None
        hub=None,           # StreamHub | None
        window_ms: float = 2.0,
        max_batch: int | None = None,
        metrics: Metrics | None = None,
        mega_max_waves: int = 1,
        mega_latency_us: float = 5000.0,
        busy_poll_us: float = 0.0,
        dropcopy=None,
        oplog=None,
        lane_id: int = 0,
    ):
        self.runner = runner
        self.sink = sink
        self.hub = hub
        # --audit: per-lane drop-copy publisher (audit/dropcopy.py) —
        # publishes one lifecycle record per storage event at the decode
        # boundary and feeds the in-process auditor. None = off.
        self.dropcopy = dropcopy
        # --oplog-ship: replication op-log shipper (replication/oplog.py)
        # — republishes every admitted dispatch's ops on the sequenced
        # oplog channel for a warm standby, strictly BEFORE the batch's
        # client completions (an acked op is always already shipped).
        # None = off. lane_id names this dispatcher's serving lane in the
        # shipped envelope so a sharded standby mirrors the routing.
        self.oplog = oplog
        self.lane_id = lane_id
        self.window_s = window_ms / 1e3
        # --busy-poll-us: spin this long before every condvar wait on the
        # drain loop (spin_get) and, via the service reading this attr,
        # on the RPC thread's completion wait (spin_result). 0 = off,
        # exactly the historical blocking behavior.
        self.busy_poll_s = max(0.0, busy_poll_us) / 1e6
        # Default: fill at most one full device dispatch per drain.
        self.max_batch = max_batch or (runner.cfg.num_symbols * runner.cfg.batch)
        self.metrics = metrics or runner.metrics
        # Megadispatch coalescing controller (--megadispatch-max-waves):
        # when the queue is still deep after a full drain, pull up to
        # (M-1) more max_batch-sized chunks WITHOUT waiting out another
        # window, so the runner stacks them into one device scan
        # (engine_runner._prepare_mega). M adapts per cycle: the
        # queue-depth target, clamped by the latency budget
        # (--megadispatch-latency-us) over the measured per-wave cost
        # EMA — deep queues amortize dispatches, light load keeps the
        # serial single-window schedule exactly (M=1 == today's loop).
        self.mega_max_waves = max(1, int(mega_max_waves))
        self.mega_latency_us = float(mega_latency_us)
        self._wave_cost_us = 0.0  # EMA, per-wave batch turnaround
        if self.mega_max_waves > 1:
            # Pre-register the controller's decision metrics so an
            # enabled-but-idle server still exports the me_megadispatch_*
            # series (scrapers see zeros, not absent names).
            self.metrics.set_gauge("megadispatch_m", 1)
            self.metrics.inc("megadispatch_coalesced", 0)
            self.metrics.inc("megadispatch_coalesced_ops", 0)
            self.metrics.inc("megadispatch_latency_clamps", 0)
        self._q: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, name="dispatcher", daemon=True)
        self._thread.start()

    def submit(self, op: EngineOp, t_ingress: float | None = None) -> Future:
        """Enqueue one validated op; the future resolves to its OpOutcome.
        The enqueue stamp is the queue-wait origin of the stage ledger;
        `t_ingress` (the RPC entry stamp, when the edge has one) lets a
        sampled trace export show the edge-ingress span too."""
        fut: Future = Future()
        self._q.put((op, fut, time.perf_counter(), t_ingress))
        return fut

    def _queue_depth(self) -> int | None:
        """Ops still waiting at drain time; None where this edge has no
        host-visible queue (the native ring subclasses — their backlog
        proxy is the inflight_ops gauge instead)."""
        return self._q.qsize()

    def close(self) -> None:
        self._stop.set()
        self._q.put(None)
        self._thread.join(timeout=10)

    # -- the drain loop ----------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                # While a staged dispatch is pending on the runner, wake at
                # window granularity so an idle lull finishes (decodes +
                # completes) it instead of stranding its clients until the
                # next op arrives. spin_get busy-polls first when
                # --busy-poll-us is set (the queue-wait tail lever).
                first = spin_get(
                    self._q,
                    self.window_s if self.runner.has_pending else None,
                    self.busy_poll_s,
                )
            except queue.Empty:
                self.runner.finish_pending()
                continue
            if first is None:
                self.runner.finish_pending()
                return
            batch = [first]
            deadline = time.perf_counter() + self.window_s
            while len(batch) < self.max_batch:
                timeout = deadline - time.perf_counter()
                if timeout <= 0:
                    break
                try:
                    item = spin_get(self._q, timeout, self.busy_poll_s)
                except queue.Empty:
                    break
                if item is None:
                    self._drain(batch)
                    self.runner.finish_pending()
                    return
                batch.append(item)
            self._coalesce(batch)
            self._drain(batch)
        self.runner.finish_pending()

    def _coalesce(self, batch) -> int:
        """The adaptive megadispatch controller: extend `batch` past
        max_batch (non-blocking — the window was already waited out) when
        the queue is deep enough to fill further waves, and return the
        resulting wave target M. Decisions export as me_megadispatch_*:
        the chosen M (gauge), coalesced-drain and op counters, and how
        often the latency budget—not queue depth—was the binding
        constraint."""
        if self.mega_max_waves <= 1:
            return 1
        depth = self._q.qsize()
        if depth <= 0:
            self.metrics.set_gauge("megadispatch_m", 1)
            return 1
        want = min(self.mega_max_waves,
                   1 + (depth + self.max_batch - 1) // self.max_batch)
        if want > 1 and self._wave_cost_us > 0 and self.mega_latency_us > 0:
            cap = max(1, int(self.mega_latency_us / self._wave_cost_us))
            if cap < want:
                self.metrics.inc("megadispatch_latency_clamps")
                want = cap
        target = want * self.max_batch
        while len(batch) < target:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is None:
                # Shutdown sentinel mid-coalesce: requeue it so the loop
                # exits at its next get; this batch still dispatches.
                self._q.put(None)
                break
            batch.append(item)
        m = (len(batch) + self.max_batch - 1) // self.max_batch
        self.metrics.set_gauge("megadispatch_m", m)
        if m > 1:
            self.metrics.inc("megadispatch_coalesced")
            self.metrics.inc("megadispatch_coalesced_ops", len(batch))
        return m

    def _drain(self, batch) -> None:
        t0 = time.perf_counter()
        ops = [op for op, _, _, _ in batch]
        futs = {id(op): fut for op, fut, _, _ in batch}
        # Stage ledger: queue wait measured from the OLDEST op's enqueue
        # (the client-felt worst case for this dispatch); build/device/
        # decode boundaries are stamped by the runner. The ingress stamp
        # (RPC entry, when the edge recorded one) extends a sampled trace
        # export to the edge-ingress span.
        ingresses = [ti for _, _, _, ti in batch if ti is not None]
        tl = DispatchTimeline(
            self.timeline_path, len(batch),
            t_enqueue=min(t for _, _, t, _ in batch), t_pop=t0,
            t_ingress=min(ingresses) if ingresses else None)
        depth = self._queue_depth()
        if depth is not None:
            self.metrics.set_gauge("queue_depth", depth)

        def on_finish(result, error):
            # Runs under the dispatch lock when this batch's results are
            # decoded (possibly a later drain iteration, an idle wakeup, a
            # checkpoint quiesce, or shutdown). The lock is held across
            # BOTH the device decode and the sink/hub enqueue:
            # CheckpointDaemon.checkpoint_now acquires the same lock, then
            # flushes the sink, then snapshots — so a batch can never be
            # applied to the book yet invisible to the flush barrier (the
            # snapshot would be ahead of SQLite and restore could
            # resurrect canceled orders). The returned thunk (future
            # completions) runs after the lock is released.
            if error is not None:
                tl.finish(self.metrics, error=error)

                def fail():
                    for _, fut, _, _ in batch:
                        if not fut.done():
                            fut.set_exception(error)
                    self.metrics.inc("dispatch_errors")
                return fail
            if self.dropcopy is not None:
                # BEFORE the sink sees the row lists: the sink's
                # coalescing thread extends the first queued batch's
                # lists in place, and the drop-copy snapshot must be of
                # THIS dispatch's rows only. (Also before the publish
                # stamp — the enqueue is stream-publish work.)
                self.dropcopy.publish(result, tl)
            if self.oplog is not None:
                self.oplog.ship(ops, tl, self.lane_id)
            self._publish(result)
            tl.stamp_publish()
            tl.finish(self.metrics)

            def complete():
                # Futures resolve only after the storage batch is
                # enqueued, so a client that sees its response and then
                # calls sink.flush() is guaranteed the flush barrier
                # covers its batch (read-your-writes).
                for outcome in result.outcomes:
                    fut = futs.get(id(outcome.op))
                    if fut is not None and not fut.done():
                        fut.set_result(outcome)
                # Any op the decode missed: fail loudly rather than hang.
                for _, fut, _, _ in batch:
                    if not fut.done():
                        fut.set_exception(
                            RuntimeError("op produced no outcome"))
                # dispatch_us = batch TURNAROUND (drain start ->
                # completion), which under pipelining includes up to one
                # batching window of pipeline residency — the client-felt
                # figure. Pure engine time is engine_dispatch_us.
                dur_us = (time.perf_counter() - t0) * 1e6
                self.metrics.ema_gauge("dispatch_us", dur_us)
                self.metrics.observe("dispatch_us", dur_us)  # -> p50/p99
                self.metrics.ema_gauge("dispatch_ops", len(batch))
                # Per-wave turnaround EMA feeding the coalescing
                # controller's latency clamp. Includes pipeline residency
                # — a deliberately conservative estimate (overstating the
                # per-wave cost only shrinks M toward the latency-safe
                # side).
                cost = dur_us / max(1, tl.waves)
                self._wave_cost_us = (
                    cost if self._wave_cost_us == 0
                    else 0.1 * cost + 0.9 * self._wave_cost_us)
            return complete

        self.runner.dispatch_pipelined(ops, on_finish, timeline=tl)

    def _publish(self, result) -> None:
        publish_result(result, self.sink, self.hub, self.metrics)


# One native-path op's completion: kind 0=submit / 1=cancel / 2=amend.
LaneOutcome = namedtuple("LaneOutcome", "kind ok order_id remaining error")


class _BatchSlot:
    """One position's future-duck in a _BatchWaiter: the drain loop's
    completion path calls done()/set_result()/set_exception() exactly as
    it does on a concurrent.futures.Future, but N slots share ONE lock
    and ONE event — a batch of 1024 ops costs two allocations per op
    instead of a Future + condition variable each (the batch edge exists
    to kill per-op cost; its completion plumbing must not reintroduce
    it)."""

    __slots__ = ("w", "i")

    def __init__(self, w, i):
        self.w = w
        self.i = i

    def done(self) -> bool:
        return self.w.slot_done(self.i)

    def set_result(self, res) -> None:
        self.w.set_slot(self.i, res, None)

    def set_exception(self, exc) -> None:
        self.w.set_slot(self.i, None, exc)


class _BatchWaiter:
    """Positional completion collector for one submitted op-record batch:
    results[i]/errors[i] land for record i, and wait() releases when every
    position resolved. The RPC handler builds the positional response
    arrays straight off it."""

    def __init__(self, n: int):
        self.n = n
        self.results: list = [None] * n
        self.errors: list = [None] * n
        self._remaining = n
        self._lock = threading.Lock()
        self._event = threading.Event()

    def slot(self, i: int) -> _BatchSlot:
        return _BatchSlot(self, i)

    def slot_done(self, i: int) -> bool:
        with self._lock:
            return self.results[i] is not None or self.errors[i] is not None

    def set_slot(self, i: int, res, exc) -> None:
        with self._lock:
            if self.results[i] is not None or self.errors[i] is not None:
                return
            if exc is None:
                self.results[i] = res
            else:
                self.errors[i] = exc
            self._remaining -= 1
            if self._remaining == 0:
                self._event.set()

    def fail_all(self, exc) -> None:
        with self._lock:
            for i in range(self.n):
                if self.results[i] is None and self.errors[i] is None:
                    self.errors[i] = exc
            self._remaining = 0
            self._event.set()

    def wait(self, timeout_s: float) -> bool:
        return self._event.wait(timeout_s)


class LaneRingDispatcher:
    """The grpcio edge's dispatcher for the native lane path (server/
    native_lanes.py): RPC threads pack ONE wide MeGwOp record and push it
    into a native ring; the drain loop pops RAW record batches and hands
    them to the C++ lane engine via NativeLanesRunner.dispatch_records.
    Host checks (directory lookups, ownership, slot capacity) happen
    natively inside the dispatch — the service keeps only proto
    validation. Futures resolve to LaneOutcome from the dispatch's
    local-tag completion section.

    Not an EngineOp dispatcher: exposes submit_record instead of submit
    (the service branches on `native_lanes`)."""

    native_lanes = True

    def __init__(
        self,
        runner,               # NativeLanesRunner
        sink=None,
        hub=None,
        window_ms: float = 2.0,
        max_batch: int | None = None,
        metrics: Metrics | None = None,
        ring_capacity: int = 1 << 16,
        busy_poll_us: float = 0.0,
        mega_max_waves: int = 1,
        dropcopy=None,
    ):
        from matching_engine_tpu import native as me_native

        if not getattr(runner, "native_lanes", False):
            raise RuntimeError("LaneRingDispatcher needs a NativeLanesRunner")
        self.runner = runner
        self.sink = sink
        self.hub = hub
        self.dropcopy = dropcopy  # --audit drop-copy publisher | None
        # The drain's batching window runs inside the native ring pop, so
        # busy-poll on this path covers the RPC threads' completion wait
        # only (the service reads this attr for spin_result).
        self.busy_poll_s = max(0.0, busy_poll_us) / 1e6
        self.window_us = max(1, int(window_ms * 1e3))
        self.max_batch = max_batch or (runner.cfg.num_symbols * runner.cfg.batch)
        # Native megadispatch: with the runner stacking M dense waves per
        # device scan, one pop may carry up to M grid-fulls — popping only
        # max_batch would cap every dispatch at one wave and the stacking
        # could never engage under the batch edge's deep backlogs.
        self._pop_cap = self.max_batch * max(
            1, int(mega_max_waves),
            int(getattr(runner, "megadispatch_max_waves", 1)))
        self.metrics = metrics or runner.metrics
        self._ring = me_native.LaneRing(ring_capacity)
        self._rec = threading.local()  # per-RPC-thread scratch record
        # tag -> (future | batch slot, t_enqueue, t_ingress | None)
        self._tags: dict[int, tuple] = {}
        self._tag_lock = threading.Lock()
        # Plain int + lock (not itertools.count): the batch edge reserves
        # n consecutive tags in one step so positional responses map back
        # by subtraction.
        self._tag_next = 1
        self._tag_alloc_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, name="lane-dispatcher",
                                        daemon=True)
        self._thread.start()

    def _alloc_tags(self, n: int) -> int:
        with self._tag_alloc_lock:
            t = self._tag_next
            self._tag_next += n
        return t

    def submit_oprec_batch(self, body: bytes, n: int,
                           t_ingress: float | None = None) -> _BatchWaiter:
        """Enqueue one validated op-record batch (domain/oprec.py records,
        magic stripped): ONE native crossing converts the payload into
        tagged ring records (tags tag0..tag0+n-1, bit 63 set for local
        completions) and ONE ring lock pushes them all. Returns the
        positional _BatchWaiter; a ring that can't hold the whole batch
        fails every position with RingFull (all-or-nothing — a split
        batch would interleave with other producers mid-overload)."""
        from matching_engine_tpu import native as me_native

        waiter = _BatchWaiter(n)
        tag0 = self._alloc_tags(n) | (1 << 63)
        recs = me_native.oprec_to_gwop(body, n, tag0)
        now = time.perf_counter()
        with self._tag_lock:
            for i in range(n):
                self._tags[tag0 + i] = (waiter.slot(i), now, t_ingress)
        if not self._ring.push_n(recs, n):
            with self._tag_lock:
                for i in range(n):
                    self._tags.pop(tag0 + i, None)
            self.metrics.inc("ring_rejects", n)
            waiter.fail_all(RingFull("op ring full"))
        return waiter

    def submit_record(self, op: int, side: int = 0, otype: int = 0,
                      price_q4: int = 0, quantity: int = 0,
                      symbol: bytes = b"", client_id: bytes = b"",
                      order_id: bytes = b"",
                      t_ingress: float | None = None) -> Future:
        """Enqueue one validated record; the future resolves to its
        LaneOutcome. Bit 63 routes the completion through the dispatch's
        local aux section instead of the gateway batch."""
        from matching_engine_tpu import native as me_native

        fut: Future = Future()
        tag = self._alloc_tags(1) | (1 << 63)
        rec = getattr(self._rec, "r", None)
        if rec is None:
            rec = self._rec.r = me_native.MeGwOp()
        me_native.pack_gwop(rec, tag, op, side=side, otype=otype,
                            price_q4=price_q4, quantity=quantity,
                            symbol=symbol, client_id=client_id,
                            order_id=order_id)
        with self._tag_lock:
            self._tags[tag] = (fut, time.perf_counter(), t_ingress)
        if not self._ring.push(rec):
            with self._tag_lock:
                self._tags.pop(tag, None)
            self.metrics.inc("ring_rejects")
            fut.set_exception(RingFull("op ring full"))
        return fut

    def close(self) -> None:
        self._stop.set()
        self._ring.close()
        self._thread.join(timeout=10)
        if self._thread.is_alive():
            print("[lane-dispatcher] drain thread busy at close; leaking ring")
        else:
            self._ring.destroy()
        with self._tag_lock:
            leftovers = list(self._tags.values())
            self._tags.clear()
        for fut, _, _ in leftovers:
            if not fut.done():
                fut.set_exception(RuntimeError("dispatcher closed"))

    def _earliest_stamps(self, recs, n: int) -> tuple[float | None,
                                                      float | None]:
        """(enqueue, ingress) stamps of the batch's OLDEST record (peek,
        not pop — completion still takes the tag). The ring is FIFO, so
        recs[0] is the first pushed and its stamp bounds the batch's
        queue wait to within the push/register race window; O(1) under
        the tag lock — a per-record scan here would re-add per-op Python
        work to the path built to avoid it."""
        with self._tag_lock:
            ent = self._tags.get(recs[0].tag) if n else None
        return (None, None) if ent is None else (ent[1], ent[2])

    def _run(self) -> None:
        from matching_engine_tpu.server.native_lanes import (
            publish_native_result,
            snapshot_records,
        )

        while not self._stop.is_set():
            buf, n = self._ring.pop_batch_raw(
                self._pop_cap, self.window_us,
                self.window_us if self.runner.has_pending else -1,
            )
            if buf is None:
                break
            if n == 0:  # idle lull with a staged dispatch: finish it
                self.runner.finish_pending()
                continue
            recs = snapshot_records(buf, n)
            t_enq, t_ing = self._earliest_stamps(recs, n)
            tl = DispatchTimeline("native-lanes", n, t_enqueue=t_enq,
                                  t_ingress=t_ing)
            self.metrics.set_gauge("inflight_ops", len(self._tags))

            def on_finish(result, error, recs=recs, n=n, tl=tl):
                if error is not None:
                    self.metrics.inc("dispatch_errors")
                    tl.finish(self.metrics, error=error)

                    def fail():
                        for i in range(n):
                            fut = self._take_tag(recs[i].tag)
                            if fut is not None and not fut.done():
                                fut.set_exception(error)
                        self.metrics.set_gauge("inflight_ops",
                                               len(self._tags))
                    return fail
                if self.dropcopy is not None:
                    # Before the sink (store_buf is immutable, but keep
                    # one ordering rule across paths).
                    self.dropcopy.publish(result, tl)
                publish_native_result(result, self.sink, self.hub,
                                      self.metrics)
                tl.stamp_publish()
                tl.finish(self.metrics)

                def complete():
                    for (tag, kind, ok, remaining, oid, err) in result.local:
                        fut = self._take_tag(tag)
                        if fut is not None and not fut.done():
                            fut.set_result(
                                LaneOutcome(kind, ok, oid, remaining, err))
                    # Any record the dispatch missed: fail loudly rather
                    # than hang its RPC thread to the timeout.
                    for i in range(n):
                        fut = self._take_tag(recs[i].tag)
                        if fut is not None and not fut.done():
                            fut.set_exception(
                                RuntimeError("op produced no outcome"))
                    # Taken tags are gone: the gauge returns to 0 on an
                    # idle server instead of freezing at the last batch.
                    self.metrics.set_gauge("inflight_ops", len(self._tags))
                return complete

            try:
                self.runner.dispatch_records(recs, n, on_finish, timeline=tl)
            except Exception as e:  # noqa: BLE001 — keep the loop alive
                self.metrics.inc("dispatch_errors")
                record_dispatch_error(self.metrics, "lane-dispatcher", e)
                print(f"[lane-dispatcher] batch failed: "
                      f"{type(e).__name__}: {e}")
                for i in range(n):
                    fut = self._take_tag(recs[i].tag)
                    if fut is not None and not fut.done():
                        fut.set_exception(e)
        self.runner.finish_pending()

    def _take_tag(self, tag: int):
        with self._tag_lock:
            ent = self._tags.pop(tag, None)
        return None if ent is None else ent[0]


class NativeRingDispatcher(BatchDispatcher):
    """BatchDispatcher whose queue + batching window run in C++ (native
    MeRing, native/me_native.cpp §2). RPC threads push fixed-size op records
    into the ring without contending the drain loop's GIL time; the
    size/time-window batching decision itself executes native. The host-side
    op metadata (OrderInfo, futures) stays in a tag map on this side.

    Requires the native library (matching_engine_tpu.native.available());
    construction raises otherwise — callers fall back to BatchDispatcher.
    """

    timeline_path = "python-ring"

    def __init__(
        self,
        runner: EngineRunner,
        sink=None,
        hub=None,
        window_ms: float = 2.0,
        max_batch: int | None = None,
        metrics: Metrics | None = None,
        ring_capacity: int = 1 << 16,
        mega_max_waves: int = 1,
        mega_latency_us: float = 5000.0,
        busy_poll_us: float = 0.0,
        dropcopy=None,
        oplog=None,
        lane_id: int = 0,
    ):
        from matching_engine_tpu import native as me_native

        if not me_native.available():
            raise RuntimeError("native library unavailable")
        self._ring = me_native.NativeRing(ring_capacity)
        # tag -> (op, future, t_enqueue, t_ingress | None)
        self._tags: dict[int, tuple[EngineOp, Future, float,
                                    float | None]] = {}
        self._tag_lock = threading.Lock()
        self._tag_seq = itertools.count(1)
        # The queue-extension controller only runs in the python-queue
        # drain loop (this class's _run pops the native ring at its own
        # batching window); the RUNNER still stacks whenever one pop
        # spans multiple waves, so the params pass through for that.
        # busy_poll likewise: the batching window waits inside the
        # native pop, so the spin only covers the service-side
        # completion wait (spin_result via the attr).
        super().__init__(runner, sink, hub, window_ms, max_batch, metrics,
                         mega_max_waves=mega_max_waves,
                         mega_latency_us=mega_latency_us,
                         busy_poll_us=busy_poll_us, dropcopy=dropcopy,
                         oplog=oplog, lane_id=lane_id)

    def submit(self, op: EngineOp, t_ingress: float | None = None) -> Future:
        fut: Future = Future()
        tag = next(self._tag_seq)
        with self._tag_lock:
            self._tags[tag] = (op, fut, time.perf_counter(), t_ingress)
        info = op.info
        # The payload fields mirror the op for native producers (the C++
        # front end pushes full records); the Python drain path keys off the
        # tag alone. sym=-1: host directory owns the symbol->slot mapping.
        ok = self._ring.push(
            tag, -1, op.op, info.side, info.otype, info.price_q4,
            info.remaining, info.oid,
        )
        if not ok:
            with self._tag_lock:
                self._tags.pop(tag, None)
            self.metrics.inc("ring_rejects")
            fut.set_exception(RingFull("op ring full"))
        return fut

    def _queue_depth(self) -> int | None:
        return None  # ops queue in the native ring; see inflight_ops

    def close(self) -> None:
        self._stop.set()
        self._ring.close()
        self._thread.join(timeout=10)
        if self._thread.is_alive():
            # Drain thread still inside a device step: leak the ring rather
            # than free memory under a live consumer.
            print("[dispatcher] drain thread busy at close; leaking ring")
        else:
            self._ring.destroy()
        # Fail anything still parked in the tag map.
        with self._tag_lock:
            leftovers = list(self._tags.values())
            self._tags.clear()
        for _, fut, _, _ in leftovers:
            if not fut.done():
                fut.set_exception(RuntimeError("dispatcher closed"))

    def _run(self) -> None:
        window_us = max(1, int(self.window_s * 1e6))
        while not self._stop.is_set():
            recs = self._ring.pop_batch(
                self.max_batch, window_us,
                window_us if self.runner.has_pending else -1,
            )
            if recs is None:
                break
            if not recs:  # idle lull with a staged dispatch: finish it
                self.runner.finish_pending()
                continue
            batch = []
            with self._tag_lock:
                for rec in recs:
                    ent = self._tags.pop(rec[0], None)
                    if ent is not None:
                        batch.append(ent)
                self.metrics.set_gauge("inflight_ops", len(self._tags))
            if batch:
                self._drain(batch)
        self.runner.finish_pending()
