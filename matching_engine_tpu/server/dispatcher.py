"""BatchDispatcher: the host-side throughput/latency knob.

The north-star architecture (BASELINE.json): the gRPC handlers don't touch
the device — they enqueue validated ops and wait on a per-op future. One
dispatcher thread drains the queue on a time/size trigger (whichever comes
first), ships a dense dispatch through the EngineRunner, completes futures,
hands storage events to the async sink, and fans stream events out to the
hubs. This replaces the reference's global `write_mu` serialization point
(matching_engine_service.cpp:102) with pipelined batches: RPC threads block
only on their own op's completion, and a whole batch costs one kernel launch.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future

from matching_engine_tpu.server.engine_runner import EngineOp, EngineRunner
from matching_engine_tpu.utils.metrics import Metrics


class BatchDispatcher:
    def __init__(
        self,
        runner: EngineRunner,
        sink=None,          # AsyncStorageSink | None
        hub=None,           # StreamHub | None
        window_ms: float = 2.0,
        max_batch: int | None = None,
        metrics: Metrics | None = None,
    ):
        self.runner = runner
        self.sink = sink
        self.hub = hub
        self.window_s = window_ms / 1e3
        # Default: fill at most one full device dispatch per drain.
        self.max_batch = max_batch or (runner.cfg.num_symbols * runner.cfg.batch)
        self.metrics = metrics or runner.metrics
        self._q: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, name="dispatcher", daemon=True)
        self._thread.start()

    def submit(self, op: EngineOp) -> Future:
        """Enqueue one validated op; the future resolves to its OpOutcome."""
        fut: Future = Future()
        self._q.put((op, fut))
        return fut

    def close(self) -> None:
        self._stop.set()
        self._q.put(None)
        self._thread.join(timeout=10)

    # -- the drain loop ----------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            first = self._q.get()
            if first is None:
                return
            batch = [first]
            deadline = time.perf_counter() + self.window_s
            while len(batch) < self.max_batch:
                timeout = deadline - time.perf_counter()
                if timeout <= 0:
                    break
                try:
                    item = self._q.get(timeout=timeout)
                except queue.Empty:
                    break
                if item is None:
                    self._drain(batch)
                    return
                batch.append(item)
            self._drain(batch)

    def _drain(self, batch) -> None:
        t0 = time.perf_counter()
        ops = [op for op, _ in batch]
        futs = {id(op): fut for op, fut in batch}
        try:
            result = self.runner.run_dispatch(ops)
        except Exception as e:  # noqa: BLE001 — fail the futures, not the loop
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(e)
            self.metrics.inc("dispatch_errors")
            return

        for outcome in result.outcomes:
            fut = futs.get(id(outcome.op))
            if fut is not None and not fut.done():
                fut.set_result(outcome)
        # Any op the decode somehow missed: fail loudly rather than hang.
        for op, fut in batch:
            if not fut.done():
                fut.set_exception(RuntimeError("op produced no outcome"))

        if self.sink is not None:
            # Non-blocking: a stalled SQLite must not backpressure the match
            # loop (we prefer losing durable-log tail to stalling matching;
            # the sink counts drops and the book checkpoint reconciles).
            if not self.sink.submit(
                orders=result.storage_orders,
                updates=result.storage_updates,
                fills=result.storage_fills,
                block=False,
            ):
                self.metrics.inc("storage_batches_dropped")
        if self.hub is not None:
            self.hub.publish_order_updates(result.order_updates)
            self.hub.publish_market_data(result.market_data)
        self.metrics.ema_gauge("dispatch_us", (time.perf_counter() - t0) * 1e6)
        self.metrics.ema_gauge("dispatch_ops", len(batch))
