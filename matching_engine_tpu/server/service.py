"""The MatchingEngine gRPC service, backed by the TPU engine pipeline.

Honors the reference's observable semantics (SURVEY.md §7 "Semantics to
preserve exactly"):
- rejects are application-level: success=false + error_message, gRPC OK
  (matching_engine_service.cpp:66-83);
- "OID-<n>" order ids, sequence resumed from storage across restarts;
- per-RPC microsecond latency logged, [SERVER]-tagged lines.

And implements what the reference declared but left stubbed or absent:
GetOrderBook from live device book snapshots (not SQL — the reference's own
storage header says the real-time book belongs in memory, storage.hpp:47),
both streaming RPCs, CancelOrder, GetMetrics.

Unlike the reference — where SubmitOrder's handler runs the whole (storage)
hot path under one mutex — this handler validates, enqueues to the
BatchDispatcher, and waits on the op's future; matching happens in dense
[S, B] device dispatches.
"""

from __future__ import annotations

import threading
import time

import grpc

from matching_engine_tpu.audit.dropcopy import AUDIT_CLIENT, AUDIT_CLIENT_FULL
from matching_engine_tpu.domain import normalize_to_q4, validate_submit
from matching_engine_tpu.feed.sequencer import (
    AUDIT_DOMAIN_KEY,
    CHANNEL_AUDIT,
    CHANNEL_MD,
    CHANNEL_OPLOG,
    CHANNEL_OU,
    OPLOG_DOMAIN_KEY,
)
from matching_engine_tpu.replication.oplog import OPLOG_CLIENT
from matching_engine_tpu.engine.kernel import (
    CANCELED,
    NEW,
    OP_AMEND,
    OP_CANCEL,
    OP_SUBMIT,
    REJECTED,
)
from matching_engine_tpu.proto import collapse_otype, pb2
from matching_engine_tpu.proto.rpc import MatchingEngineServicer
from matching_engine_tpu.server.dispatcher import (
    BatchDispatcher,
    RingFull,
    spin_result,
)
from matching_engine_tpu.server.engine_runner import EngineOp, EngineRunner, OrderInfo
from matching_engine_tpu.server.streams import StreamHub
from matching_engine_tpu.utils.metrics import Metrics
from matching_engine_tpu.utils.obs import STAGE_EDGE_INGRESS


class MatchingEngineService(MatchingEngineServicer):
    def __init__(
        self,
        runner: EngineRunner,
        dispatcher: BatchDispatcher,
        hub: StreamHub,
        metrics: Metrics | None = None,
        log: bool = True,
        shards=None,  # server/shards.ServingShards | None
        book_cache_ms: float = 0.0,
        proto_reuse: bool = False,
        admission=None,  # server/admission.AdmissionScreens | None
    ):
        self.runner = runner
        self.dispatcher = dispatcher
        self.hub = hub
        self.metrics = metrics or runner.metrics
        self.log = log
        # Vectorized per-client admission screens (server/admission.py):
        # one shared instance screens every ingress path — the bulk
        # paths (SubmitOrderBatch / SubmitOrderStream / the shm poller /
        # the gateway's forwarded batch) as numpy passes, the per-op
        # RPCs as 1-record batches through screen_one.
        self.admission = admission
        # Partitioned serving (server/shards.py): requests route to one of
        # K independent lanes — submits/books by symbol shard, cancels/
        # amends by the order id's birth lane. self.runner/self.dispatcher
        # stay lane 0 for the shard-agnostic surfaces (metrics, streams).
        self.shards = shards
        # --book-cache-ms: conflated latest-state book snapshots. A
        # GetOrderBook burst otherwise contends the runner's snapshot
        # lock — which every device step holds — so read traffic lands
        # directly on the dispatch path's tail. With a TTL, reads within
        # it are served from the last materialized response (staleness
        # bounded by the TTL; same contract as a conflated feed channel).
        self._book_cache_s = max(0.0, book_cache_ms) / 1e3
        self._book_cache: dict[str, tuple[float, object]] = {}
        # Eviction bound sized to the VENUE's symbol axis: under
        # --serve-shards, runner is lane 0 and its cfg holds the K-way
        # split — a per-lane bound would make an all-symbols read burst
        # overflow-clear the cache it exists to serve.
        k = shards.num_shards if shards is not None else 1
        self._book_cache_cap = 4 * runner.cfg.num_symbols * k
        # --proto-reuse: recycle one completion proto per (RPC thread,
        # message type) instead of allocating per response. Safe because
        # grpc serializes a unary response on the handler's own thread
        # before that thread takes another RPC; stream events are NOT
        # reused (they alias subscriber queues and the feed store).
        self._proto_reuse = proto_reuse
        self._tl_protos = threading.local()
        # Warm-standby replication (replication/): a --standby server
        # keeps the mutation RPCs closed until promotion flips this off
        # (reads and streams serve throughout). `replica` is the
        # StandbyReplica driving the engine from the primary's op log;
        # build_server wires both after construction.
        self.read_only = False
        self.replica = None
        # True on an --oplog-ship primary. The auction uncross mutates
        # books outside the dispatcher drain loops (engine_runner.
        # run_auction under the dispatch lock), so it never crosses the
        # op-log shipper — RunAuction must reject rather than silently
        # diverge every standby.
        self.oplog_ship = False

    def _log(self, msg: str) -> None:
        if self.log:
            print(f"[SERVER] {msg}")

    def _wait(self, fut, dispatcher, timeout: float = 30.0):
        """The RPC thread's completion wait: busy-polls first when the
        dispatcher carries --busy-poll-us (the wakeup after this op's
        dispatch decodes is a condvar round trip squarely in the
        client-felt tail), then blocks as before. Result semantics are
        identical either way."""
        return spin_result(fut, timeout,
                           getattr(dispatcher, "busy_poll_s", 0.0))

    def _completion(self, cls, **kw):
        """Build a unary completion proto, recycling a thread-local
        instance under --proto-reuse (allocation + field-descriptor
        setup per response is measurable on the submit tail). Reuse is
        safe for UNARY completions only: gRPC serializes the return
        value on this worker thread before it picks up another RPC.
        Never use for stream events — those alias subscriber queues and
        the feed retransmission store long after the handler returns."""
        if not self._proto_reuse:
            return cls(**kw)
        store = self._tl_protos.__dict__
        msg = store.get(cls.__name__)
        if msg is None:
            msg = store[cls.__name__] = cls()
        else:
            msg.Clear()
        for k, v in kw.items():
            setattr(msg, k, v)
        return msg

    # -- shard routing -----------------------------------------------------

    def _lane_for_symbol(self, symbol: str):
        if self.shards is None:
            return self.runner, self.dispatcher
        lane = self.shards.lane_for_symbol(symbol)
        return lane.runner, lane.dispatcher

    def _lane_for_order(self, order_id: str):
        if self.shards is None:
            return self.runner, self.dispatcher
        lane = self.shards.lane_for_order(order_id)
        return lane.runner, lane.dispatcher

    # Application-level reject every mutation RPC answers on a standby
    # (the SubmitOrder reject convention: success=false, gRPC OK).
    _STANDBY_ERR = ("standby replica is read-only (Promote it, or submit "
                    "to the primary)")

    # -- SubmitOrder -------------------------------------------------------

    def SubmitOrder(self, request, context):
        t0 = time.perf_counter()
        self.metrics.inc("rpc_submit")
        if self.read_only:
            self.metrics.inc("orders_rejected")
            return self._completion(pb2.OrderResponse, success=False,
                                    error_message=self._STANDBY_ERR)
        side_s = pb2.Side.Name(request.side) if request.side in (1, 2) else str(request.side)
        type_s = (
            pb2.OrderType.Name(request.order_type)
            if request.order_type in (pb2.LIMIT, pb2.MARKET)
            else str(request.order_type)  # proto3 open enums: log raw, don't crash
        )
        if request.tif:
            type_s += "/" + (
                pb2.TimeInForce.Name(request.tif)
                if request.tif in (pb2.TIF_IOC, pb2.TIF_FOK)
                else str(request.tif)
            )
        self._log(
            f"SubmitOrder client={request.client_id} symbol={request.symbol} "
            f"side={side_s} type={type_s} "
            f"price={request.price}@{request.scale} qty={request.quantity} "
            f"peer={context.peer() if context else '-'}"
        )

        # Symbol-shard routing happens before any state is touched: every
        # check and allocation below runs against the one lane that owns
        # this symbol (the single-lane server routes to itself).
        runner, dispatcher = self._lane_for_symbol(request.symbol)
        err = validate_submit(request)
        otype = collapse_otype(request.order_type, request.tif)
        if err is None and otype is None:
            err = "unsupported (order_type, tif) combination"
        if (err is None and self.admission is not None
                and self.admission.enabled):
            # The per-op edge obeys the same admission rules as the bulk
            # paths: one 1-record batch through the shared screens,
            # BEFORE any slot/handle allocation (a screened-out op must
            # consume nothing).
            price_q4 = (0 if request.order_type == pb2.MARKET
                        else normalize_to_q4(request.price, request.scale))
            err = self.admission.screen_one(
                1, request.side, otype, price_q4, request.quantity,
                request.symbol.encode(), request.client_id.encode())
        native = getattr(dispatcher, "native_lanes", False)
        if err is None and native:
            # Native lane path: proto validation stays here; the host
            # checks (auction mode, slot capacity) and id/handle/slot
            # assignment run inside the C++ dispatch, atomic with the
            # RunAuction mode flip. One wide record crosses per op.
            if not runner.owns_symbol(request.symbol):
                err = f"symbol {request.symbol} is homed on another host"
            else:
                price_q4 = (
                    0 if request.order_type == pb2.MARKET
                    else normalize_to_q4(request.price, request.scale)
                )
                return self._finish_submit_native(
                    request, t0, otype, price_q4, dispatcher)
        if (err is None and runner.auction_mode
                and otype != pb2.LIMIT):
            # MARKET/IOC/FOK all demand immediate execution; a call period
            # has no continuous matching to execute against.
            err = ("only GTC LIMIT orders are accepted during an auction "
                   "call period")
        if err is None and not runner.owns_symbol(request.symbol):
            # Multi-process routing invariant: the client (or front-end
            # router) must send this symbol to its home host.
            err = f"symbol {request.symbol} is homed on another host"
        # slot_acquire also counts one live order on the slot, so the slot
        # cannot be recycled between this validation and the dispatch.
        if err is None and runner.slot_acquire(request.symbol) is None:
            err = "symbol capacity exhausted (engine symbol axis is full)"
        if err is not None:
            self.metrics.inc("orders_rejected")
            self._log(f"reject: {err}")
            return self._completion(pb2.OrderResponse, success=False,
                                    error_message=err)

        price_q4 = (
            0 if request.order_type == pb2.MARKET
            else normalize_to_q4(request.price, request.scale)
        )
        oid_num, order_id = runner.assign_oid()
        info = OrderInfo(
            oid=oid_num, order_id=order_id, client_id=request.client_id,
            symbol=request.symbol, side=request.side,
            otype=otype, price_q4=price_q4,
            quantity=request.quantity, remaining=request.quantity, status=0,
            handle=runner.assign_handle(),
        )
        # Edge-ingress stage: RPC entry -> queue push (validation, id
        # assignment, OrderInfo build). The queue-wait stage picks up at
        # the enqueue stamp the dispatcher records.
        self.metrics.observe(
            STAGE_EDGE_INGRESS, (time.perf_counter() - t0) * 1e6)
        try:
            # Always OP_SUBMIT here: auction-mode classification happens
            # in the runner under the dispatch lock (atomic with the
            # RunAuction mode flip; the edge read would race). t0 rides
            # along so a sampled trace export shows the edge-ingress span.
            outcome = self._wait(
                dispatcher.submit(EngineOp(OP_SUBMIT, info), t_ingress=t0),
                dispatcher)
        except RingFull:
            # Known-unqueued: the device never saw this op, recycle now.
            runner.release_unqueued(info)
            self.metrics.inc("orders_rejected")
            self._log(f"reject {order_id}: op ring full")
            return self._completion(
                pb2.OrderResponse,
                order_id=order_id, success=False, error_message="server overloaded"
            )
        except Exception as e:  # noqa: BLE001 — engine failure => app-level reject
            # The op may still be queued (timeout) or half-applied (dispatch
            # error), so the handle/slot must NOT be recycled here — a rare
            # bounded leak beats handle reuse against a possibly-live order.
            self.metrics.inc("orders_errored")
            self._log(f"engine error for {order_id}: {e}")
            return self._completion(
                pb2.OrderResponse,
                order_id=order_id, success=False, error_message="engine error"
            )

        dur_us = (time.perf_counter() - t0) * 1e6
        # Disambiguated registry keys: the EMA lands as submit_rpc_us_ema
        # (suffix applied inside ema_gauge), the window as _p50/_p99.
        self.metrics.ema_gauge("submit_rpc_us", dur_us)
        self.metrics.observe("submit_rpc_us", dur_us)  # -> submit_rpc_us_p50/p99
        if outcome.status == REJECTED and outcome.error:
            self.metrics.inc("orders_rejected")
            self._log(f"rejected {order_id}: {outcome.error} ({dur_us:.0f}us)")
            return self._completion(
                pb2.OrderResponse,
                order_id=order_id, success=False, error_message=outcome.error
            )
        self.metrics.inc("orders_accepted")
        self._log(
            f"accepted {order_id} status={pb2.OrderUpdate.Status.Name(outcome.status)} "
            f"filled={outcome.filled} remaining={outcome.remaining} ({dur_us:.0f}us)"
        )
        return self._completion(pb2.OrderResponse, order_id=order_id,
                                success=True)

    def _finish_submit_native(self, request, t0, otype, price_q4,
                              dispatcher=None):
        """SubmitOrder tail on the lane path (LaneRingDispatcher): the
        accept/reject metrics come from the dispatch's aux counters."""
        from matching_engine_tpu.server.dispatcher import RingFull

        if dispatcher is None:
            dispatcher = self.dispatcher
        # Same edge-ingress stage as the Python path: RPC entry -> ring
        # push (proto validation + record pack happen per op either way).
        self.metrics.observe(
            STAGE_EDGE_INGRESS, (time.perf_counter() - t0) * 1e6)
        try:
            outcome = self._wait(dispatcher.submit_record(
                1, side=request.side, otype=otype, price_q4=price_q4,
                quantity=request.quantity, symbol=request.symbol.encode(),
                client_id=request.client_id.encode(), t_ingress=t0,
            ), dispatcher)
        except RingFull:
            self.metrics.inc("orders_rejected")
            self._log("reject: op ring full")
            return self._completion(
                pb2.OrderResponse,
                success=False, error_message="server overloaded")
        except Exception as e:  # noqa: BLE001 — engine failure => app reject
            self.metrics.inc("orders_errored")
            self._log(f"engine error: {e}")
            return self._completion(
                pb2.OrderResponse,
                success=False, error_message="engine error")
        dur_us = (time.perf_counter() - t0) * 1e6
        self.metrics.ema_gauge("submit_rpc_us", dur_us)
        self.metrics.observe("submit_rpc_us", dur_us)
        if not outcome.ok:
            self._log(f"rejected {outcome.order_id or '(pre-id)'}: "
                      f"{outcome.error} ({dur_us:.0f}us)")
            return self._completion(
                pb2.OrderResponse,
                order_id=outcome.order_id, success=False,
                error_message=outcome.error)
        self._log(f"accepted {outcome.order_id} ({dur_us:.0f}us)")
        return self._completion(pb2.OrderResponse,
                                order_id=outcome.order_id, success=True)

    # -- SubmitOrderBatch --------------------------------------------------

    # Records per request: bounds per-RPC memory (a cap batch is ~25 MB of
    # records); recorded flows slice themselves into multiple requests.
    _BATCH_RECORD_CAP = 1 << 16
    _BATCH_TIMEOUT_S = 60.0

    def SubmitOrderBatch(self, request, context):
        """The batch-native edge: one RPC carries N packed op-records
        (domain/oprec.py) and returns N positional statuses — the per-op
        network edge (~160µs/op measured round 5) amortizes over the
        batch, and one bad op rejects its position, never the batch.
        Records route to their owning lane (submits by symbol shard,
        cancels/amends by order id) exactly like the per-op RPCs; on a
        native-lane dispatcher the whole group crosses as ONE payload
        (dispatcher.submit_oprec_batch), on the python path each record
        becomes the same EngineOp the per-op edge builds — the parity
        oracle the batch tests pin against."""
        from matching_engine_tpu.domain import oprec

        t0 = time.perf_counter()
        m = self.metrics
        m.inc("edge_batches")
        if self.read_only:
            return pb2.OrderBatchResponse(success=False,
                                          error_message=self._STANDBY_ERR)
        try:
            arr = oprec.decode_payload(request.ops,
                                       max_records=self._BATCH_RECORD_CAP)
        except oprec.OpRecError as e:
            m.inc("edge_codec_errors")
            self._log(f"SubmitOrderBatch codec reject: {e}")
            return pb2.OrderBatchResponse(success=False,
                                          error_message=str(e))
        n = len(arr)
        m.inc("edge_batch_ops", n)
        m.inc("edge_batch_bytes", len(request.ops))
        m.observe("edge_batch_size", n)
        self._log(f"SubmitOrderBatch ops={n} bytes={len(request.ops)} "
                  f"peer={context.peer() if context else '-'}")
        ok, oids, errs, rems, _, _ = self.run_oprec_records(arr, t0=t0)
        rejects = n - sum(ok)
        if rejects:
            m.inc("edge_batch_rejects", rejects)
        dur_us = (time.perf_counter() - t0) * 1e6
        m.ema_gauge("submit_rpc_us", dur_us)
        m.observe("submit_rpc_us", dur_us)
        self._log(f"SubmitOrderBatch done ops={n} rejects={rejects} "
                  f"({dur_us:.0f}us)")
        # Never through _completion: repeated fields don't setattr, so
        # the proto-reuse recycling path cannot serve batch responses.
        return pb2.OrderBatchResponse(success=True, ok=ok, order_id=oids,
                                      error=errs, remaining=rems)

    def run_oprec_records(self, arr, t0: float | None = None):
        """Screen + dispatch one decoded record array through the shared
        batch machinery (the structural flaw screen, the vectorized
        admission screens, lane routing, two-phase enqueue/finish) and
        return positional (ok, oids, errs, rems, reasons, flaws).
        `reasons` is the admission pass's REASON_* array (None when
        admission is off) and `flaws` the pre-dispatch screen verdicts —
        the shm poller keys its response codes off both. Every bulk
        ingress path funnels here: SubmitOrderBatch, SubmitOrderStream,
        the shm ring poller, and the gateway's forwarded batch verb."""
        from matching_engine_tpu.domain import oprec

        if t0 is None:
            t0 = time.perf_counter()
        m = self.metrics
        n = len(arr)
        ok: list[bool] = [False] * n
        oids: list[str] = [""] * n
        errs: list[str] = [""] * n
        rems: list[int] = [0] * n
        reasons = None
        flaws: list = [None] * n
        if n:
            flaws = oprec.record_flaws(arr)
            if self.admission is not None and self.admission.enabled:
                reasons = self.admission.screen(arr, flaws)
            clean = [i for i in range(n) if flaws[i] is None]
            for i in range(n):
                if flaws[i] is not None:
                    errs[i] = flaws[i]
                    m.inc("orders_rejected")
            deadline = t0 + self._BATCH_TIMEOUT_S
            # Two phases across lane groups: enqueue EVERY group's slice
            # first, then collect completions — waiting per group would
            # serialize the partitioned lanes the routing exists to
            # parallelize (RPC latency = sum of lane turnarounds instead
            # of their max, with later lanes' hardware idle meanwhile).
            finishers = [
                self._batch_group(runner, dispatcher, arr, idxs, ok, oids,
                                  errs, rems, t0, deadline, routed)
                for runner, dispatcher, idxs, routed in self._batch_groups(
                    arr, clean)]
            # Edge-ingress stage: entry -> every lane's slice enqueued
            # (decode, flaw + admission screens, routing, ring pushes).
            m.observe(STAGE_EDGE_INGRESS, (time.perf_counter() - t0) * 1e6)
            for finish in finishers:
                finish()
        return ok, oids, errs, rems, reasons, flaws

    # -- SubmitOrderStream -------------------------------------------------

    # Total records across one stream: bounds the response arrays (the
    # single positional reply spans the whole stream).
    _STREAM_RECORD_CAP = 1 << 20

    def SubmitOrderStream(self, request_iterator, context):
        """Client-streaming ingest for remote flow that can't batch
        client-side: the client sends a stream of OrderBatchRequest
        chunks (each the usual oprec payload — a chunk may carry ONE
        record) and the server drains them into the same vectorized
        screen + dispatch pipeline as SubmitOrderBatch, chunk by chunk,
        so dispatch overlaps the stream instead of waiting for its end.
        One OrderBatchResponse answers the whole stream with positional
        arrays in arrival order. An undecodable chunk fails the stream
        (success=false) — everything already dispatched stays dispatched,
        mirroring the batch edge's payload-poisoning rule per chunk."""
        from matching_engine_tpu.domain import oprec

        t0 = time.perf_counter()
        m = self.metrics
        m.inc("edge_streams")
        if self.read_only:
            return pb2.OrderBatchResponse(success=False,
                                          error_message=self._STANDBY_ERR)
        all_ok: list[bool] = []
        all_oids: list[str] = []
        all_errs: list[str] = []
        all_rems: list[int] = []
        chunks = 0
        for req in request_iterator:
            try:
                arr = oprec.decode_payload(
                    req.ops, max_records=self._BATCH_RECORD_CAP)
            except oprec.OpRecError as e:
                m.inc("edge_codec_errors")
                self._log(f"SubmitOrderStream codec reject: {e}")
                return pb2.OrderBatchResponse(success=False,
                                              error_message=str(e))
            if len(all_ok) + len(arr) > self._STREAM_RECORD_CAP:
                return pb2.OrderBatchResponse(
                    success=False,
                    error_message=(f"stream exceeds "
                                   f"{self._STREAM_RECORD_CAP} records"))
            chunks += 1
            m.inc("edge_stream_ops", len(arr))
            ok, oids, errs, rems, _, _ = self.run_oprec_records(arr)
            all_ok.extend(ok)
            all_oids.extend(oids)
            all_errs.extend(errs)
            all_rems.extend(rems)
        rejects = len(all_ok) - sum(all_ok)
        if rejects:
            m.inc("edge_batch_rejects", rejects)
        dur_us = (time.perf_counter() - t0) * 1e6
        self._log(f"SubmitOrderStream done chunks={chunks} "
                  f"ops={len(all_ok)} rejects={rejects} ({dur_us:.0f}us)")
        return pb2.OrderBatchResponse(success=True, ok=all_ok,
                                      order_id=all_oids, error=all_errs,
                                      remaining=all_rems)

    def _batch_groups(self, arr, clean: list[int]):
        """Split a batch's clean record indices across serving lanes:
        submits by symbol shard, cancels/amends by the order id's birth
        lane — the same routing the per-op RPCs use. Single-lane servers
        skip the per-record routing decode entirely."""
        from matching_engine_tpu.domain.oprec import OPREC_SUBMIT

        if self.shards is None:
            yield self.runner, self.dispatcher, clean, False
            return
        from matching_engine_tpu.domain.oprec import (
            record_order_id,
            record_symbol,
        )

        groups: dict[int, list[int]] = {}
        for i in clean:
            r = arr[i]
            if int(r["op"]) == OPREC_SUBMIT:
                sym = record_symbol(r).decode(errors="replace")
                lane = self.shards.lane_for_symbol(sym)
            else:
                oid = record_order_id(r).decode(errors="replace")
                lane = self.shards.lane_for_order(oid)
            groups.setdefault(lane.shard_id, []).append(i)
        for shard_id, idxs in groups.items():
            lane = self.shards.lanes[shard_id]
            yield lane.runner, lane.dispatcher, idxs, True

    def _batch_group(self, runner, dispatcher, arr, idxs, ok, oids, errs,
                     rems, t0, deadline, routed=False):
        """ENQUEUE one lane group's slice; returns the finisher that
        waits for its completions and fills the positional arrays."""
        if getattr(dispatcher, "native_lanes", False):
            return self._batch_group_native(runner, dispatcher, arr, idxs,
                                            ok, oids, errs, rems, t0,
                                            deadline, routed)
        return self._batch_group_python(runner, dispatcher, arr, idxs, ok,
                                        oids, errs, rems, t0, deadline)

    @staticmethod
    def _noop_finish() -> None:
        return None

    def _batch_group_native(self, runner, dispatcher, arr, idxs, ok, oids,
                            errs, rems, t0, deadline, routed=False):
        """One lane's batch slice on the native-lane path: the records
        cross as ONE payload — conversion to tagged ring records, the
        bulk ring push, host checks, id assignment, and UTF-8 validation
        all run in C++; python touches the batch per POSITION only to
        read the outcome. `routed` slices already passed the shard
        router's hash — the same cut the lane's owns_filter applies — so
        they skip the per-record ownership scan the one-crossing design
        exists to avoid. Enqueues only; returns the completion
        finisher."""
        from matching_engine_tpu.domain import oprec

        count = len(idxs)
        if count == 0:
            return self._noop_finish
        if not routed and not runner.owns_all_symbols():
            # Multi-host homing: the rare config where ownership must be
            # checked by name. Reject foreign symbols positionally; the
            # remainder still crosses as one payload.
            kept = []
            for i in idxs:
                op, _s, _o, _p, _q, sym_b, _c, _oid = oprec.record_fields(
                    arr[i])
                if op == oprec.OPREC_SUBMIT:
                    try:
                        sym = sym_b.decode()
                    except UnicodeDecodeError:
                        errs[i] = "invalid request encoding"
                        self.metrics.inc("orders_rejected")
                        continue
                    if not runner.owns_symbol(sym):
                        errs[i] = f"symbol {sym} is homed on another host"
                        self.metrics.inc("orders_rejected")
                        continue
                kept.append(i)
            idxs, count = kept, len(kept)
            if count == 0:
                return self._noop_finish
        body = arr[idxs].tobytes() if len(idxs) != len(arr) else arr.tobytes()
        try:
            waiter = dispatcher.submit_oprec_batch(body, count, t_ingress=t0)
        except Exception as e:  # noqa: BLE001 — converter/ring fault: the
            # records were pre-screened, so this is server-side trouble;
            # fail the slice positionally, never the RPC.
            self.metrics.inc("orders_errored", count)
            self._log(f"batch enqueue failed: {type(e).__name__}: {e}")
            for i in idxs:
                errs[i] = "engine error"
            return self._noop_finish

        def finish() -> None:
            if not waiter.wait(max(0.0, deadline - time.perf_counter())):
                waiter.fail_all(TimeoutError("batch dispatch timed out"))
            for j in range(count):
                i = idxs[j]
                out = waiter.results[j]
                if out is None:
                    exc = waiter.errors[j]
                    self.metrics.inc("orders_rejected"
                                     if isinstance(exc, RingFull)
                                     else "orders_errored")
                    errs[i] = ("server overloaded"
                               if isinstance(exc, RingFull)
                               else "engine error")
                    continue
                oids[i] = out.order_id or ""
                if out.ok:
                    ok[i] = True
                    if out.kind == 2:
                        rems[i] = out.remaining
                else:
                    errs[i] = out.error or (
                        "amend rejected" if out.kind == 2
                        else "order not open" if out.kind == 1
                        else "rejected")
        return finish

    def _batch_group_python(self, runner, dispatcher, arr, idxs, ok, oids,
                            errs, rems, t0, deadline):
        """One lane's batch slice on the python path — per record exactly
        the checks/EngineOp the per-op handlers run (the parity oracle),
        with ALL ops enqueued before any completion wait so the whole
        slice rides the same dispatch window. Enqueues only; returns the
        completion finisher."""
        from matching_engine_tpu.domain import oprec

        m = self.metrics
        pending: list[tuple[int, int, object]] = []  # (pos, kind, future)
        # Intra-batch targets resolve against the PRE-BATCH directory —
        # the C++ lane build's rule (its host checks run against the
        # directory as of batch start). Without this, a cancel naming a
        # submit from the same payload would race the dispatcher's
        # registration: sometimes "unknown order id", sometimes applied.
        batch_new: set[str] = set()
        for i in idxs:
            (op, side, otype, price_q4, qty, sym_b, cid_b,
             oid_b) = oprec.record_fields(arr[i])
            try:
                symbol = sym_b.decode()
                client_id = cid_b.decode()
                order_id = oid_b.decode()
            except UnicodeDecodeError:
                errs[i] = "invalid request encoding"
                m.inc("orders_rejected")
                continue
            if op == oprec.OPREC_SUBMIT:
                if runner.auction_mode and otype != pb2.LIMIT:
                    errs[i] = ("only GTC LIMIT orders are accepted during "
                               "an auction call period")
                    m.inc("orders_rejected")
                    continue
                if not runner.owns_symbol(symbol):
                    errs[i] = f"symbol {symbol} is homed on another host"
                    m.inc("orders_rejected")
                    continue
                if runner.slot_acquire(symbol) is None:
                    errs[i] = ("symbol capacity exhausted (engine symbol "
                               "axis is full)")
                    m.inc("orders_rejected")
                    continue
                oid_num, oid_str = runner.assign_oid()
                info = OrderInfo(
                    oid=oid_num, order_id=oid_str, client_id=client_id,
                    symbol=symbol, side=side, otype=otype,
                    price_q4=price_q4, quantity=qty, remaining=qty,
                    status=0, handle=runner.assign_handle())
                oids[i] = oid_str
                batch_new.add(oid_str)
                try:
                    fut = dispatcher.submit(EngineOp(OP_SUBMIT, info),
                                            t_ingress=t0)
                except RingFull:
                    runner.release_unqueued(info)
                    errs[i] = "server overloaded"
                    m.inc("orders_rejected")
                    continue
                pending.append((i, 0, fut))
                continue
            oids[i] = order_id
            info = (None if order_id in batch_new
                    else runner.orders_by_id.get(order_id))
            if info is None:
                errs[i] = "unknown order id"
                continue
            if info.client_id != client_id:
                errs[i] = "order belongs to a different client"
                continue
            kind = 2 if op == oprec.OPREC_AMEND else 1
            e = (EngineOp(OP_AMEND, info, amend_qty=qty) if kind == 2
                 else EngineOp(OP_CANCEL, info, cancel_requester=client_id))
            try:
                pending.append((i, kind, dispatcher.submit(e,
                                                           t_ingress=t0)))
            except RingFull:
                errs[i] = "server overloaded"

        def finish() -> None:
            for i, kind, fut in pending:
                try:
                    outcome = fut.result(
                        timeout=max(0.0, deadline - time.perf_counter()))
                except Exception:  # noqa: BLE001 — engine/timeout =>
                    # app-level reject
                    m.inc("orders_errored")
                    errs[i] = "engine error"
                    continue
                if kind == 0:
                    if outcome.status == REJECTED and outcome.error:
                        m.inc("orders_rejected")
                        errs[i] = outcome.error
                    else:
                        m.inc("orders_accepted")
                        ok[i] = True
                elif kind == 1:
                    if outcome.status == CANCELED:
                        m.inc("orders_canceled")
                        ok[i] = True
                    else:
                        errs[i] = outcome.error or "order not open"
                else:
                    if outcome.status == NEW:
                        m.inc("orders_amended")
                        ok[i] = True
                        rems[i] = outcome.remaining
                    else:
                        errs[i] = outcome.error or "amend rejected"
        return finish

    # -- CancelOrder -------------------------------------------------------

    def CancelOrder(self, request, context):
        self.metrics.inc("rpc_cancel")
        if self.read_only:
            return pb2.CancelResponse(
                order_id=request.order_id, success=False,
                error_message=self._STANDBY_ERR)
        if not request.client_id:
            return pb2.CancelResponse(
                order_id=request.order_id, success=False,
                error_message="client_id is required",
            )
        if self.admission is not None and self.admission.enabled:
            aerr = self.admission.screen_one(
                2, 0, 0, 0, 0, b"", request.client_id.encode())
            if aerr is not None:
                return pb2.CancelResponse(
                    order_id=request.order_id, success=False,
                    error_message=aerr)
        runner, dispatcher = self._lane_for_order(request.order_id)
        if getattr(dispatcher, "native_lanes", False):
            return self._cancel_native(request, dispatcher)
        info = runner.orders_by_id.get(request.order_id)
        if info is None:
            return pb2.CancelResponse(
                order_id=request.order_id, success=False,
                error_message="unknown order id",
            )
        if info.client_id != request.client_id:
            return pb2.CancelResponse(
                order_id=request.order_id, success=False,
                error_message="order belongs to a different client",
            )
        try:
            outcome = self._wait(dispatcher.submit(
                EngineOp(OP_CANCEL, info, cancel_requester=request.client_id)
            ), dispatcher)
        except RingFull:
            # Cancels hold no handle/slot — only the message differs.
            return pb2.CancelResponse(
                order_id=request.order_id, success=False,
                error_message="server overloaded",
            )
        except Exception:  # noqa: BLE001
            return pb2.CancelResponse(
                order_id=request.order_id, success=False, error_message="engine error"
            )
        if outcome.status == CANCELED:
            self.metrics.inc("orders_canceled")
            return pb2.CancelResponse(order_id=request.order_id, success=True)
        return pb2.CancelResponse(
            order_id=request.order_id, success=False,
            error_message=outcome.error or "order not open",
        )

    @staticmethod
    def _target_fits_record(request):
        """Oversized cancel/amend identifiers answered at the edge with
        the SAME errors the Python path's directory lookup produces —
        never let them reach pack_gwop, whose fixed record fields would
        raise and surface as 'engine error' (an id that can't fit the
        record can't name a live order either)."""
        from matching_engine_tpu.domain.order import MAX_CLIENT_ID_BYTES

        if len(request.order_id.encode()) > 36:  # MeGwOp.order_id
            return "unknown order id"
        if len(request.client_id.encode()) > MAX_CLIENT_ID_BYTES:
            return "order belongs to a different client"
        return None

    def _cancel_native(self, request, dispatcher=None):
        """CancelOrder tail on the lane path: the directory lookup and
        ownership check run natively inside the dispatch (accept/cancel
        metrics come from the dispatch's aux counters, same as the Python
        finalize — no per-RPC increment here)."""
        from matching_engine_tpu.server.dispatcher import RingFull

        if dispatcher is None:
            dispatcher = self.dispatcher
        err = self._target_fits_record(request)
        if err is not None:
            return pb2.CancelResponse(
                order_id=request.order_id, success=False, error_message=err)
        try:
            outcome = self._wait(dispatcher.submit_record(
                2, order_id=request.order_id.encode(),
                client_id=request.client_id.encode(),
            ), dispatcher)
        except RingFull:
            return pb2.CancelResponse(
                order_id=request.order_id, success=False,
                error_message="server overloaded",
            )
        except Exception:  # noqa: BLE001
            return pb2.CancelResponse(
                order_id=request.order_id, success=False,
                error_message="engine error",
            )
        if outcome.ok:
            return pb2.CancelResponse(order_id=request.order_id, success=True)
        return pb2.CancelResponse(
            order_id=request.order_id, success=False,
            error_message=outcome.error or "order not open",
        )

    # -- AmendOrder --------------------------------------------------------

    def AmendOrder(self, request, context):
        """Priority-preserving quantity reduction (proto AmendOrder): the
        order keeps its price and time priority; only a strict reduction
        to a positive quantity succeeds. Allowed in call periods too — an
        amend-down never crosses anything."""
        self.metrics.inc("rpc_amend")
        if self.read_only:
            return pb2.AmendResponse(
                order_id=request.order_id, success=False,
                error_message=self._STANDBY_ERR)
        if not request.client_id:
            return pb2.AmendResponse(
                order_id=request.order_id, success=False,
                error_message="client_id is required",
            )
        if request.new_quantity <= 0:
            return pb2.AmendResponse(
                order_id=request.order_id, success=False,
                error_message="new_quantity must be positive",
            )
        from matching_engine_tpu.domain.order import MAX_QUANTITY
        if request.new_quantity > MAX_QUANTITY:
            # The bulk edges (record_flaws / me_oprec_flaws code 10) have
            # always enforced the engine cap on amends; the per-op paths
            # screen it too now — byte-identical wording on both edges
            # (the C++ gateway runs perop_flaw, this mirrors it).
            return pb2.AmendResponse(
                order_id=request.order_id, success=False,
                error_message=(f"quantity exceeds the engine maximum "
                               f"{MAX_QUANTITY} (int32 book-sum safety "
                               f"bound)"),
            )
        if self.admission is not None and self.admission.enabled:
            aerr = self.admission.screen_one(
                3, 0, 0, 0, request.new_quantity, b"",
                request.client_id.encode())
            if aerr is not None:
                return pb2.AmendResponse(
                    order_id=request.order_id, success=False,
                    error_message=aerr)
        runner, dispatcher = self._lane_for_order(request.order_id)
        if getattr(dispatcher, "native_lanes", False):
            return self._amend_native(request, dispatcher)
        info = runner.orders_by_id.get(request.order_id)
        if info is None:
            return pb2.AmendResponse(
                order_id=request.order_id, success=False,
                error_message="unknown order id",
            )
        if info.client_id != request.client_id:
            return pb2.AmendResponse(
                order_id=request.order_id, success=False,
                error_message="order belongs to a different client",
            )
        try:
            outcome = self._wait(dispatcher.submit(
                EngineOp(OP_AMEND, info, amend_qty=request.new_quantity)
            ), dispatcher)
        except RingFull:
            return pb2.AmendResponse(
                order_id=request.order_id, success=False,
                error_message="server overloaded",
            )
        except Exception:  # noqa: BLE001
            return pb2.AmendResponse(
                order_id=request.order_id, success=False,
                error_message="engine error",
            )
        if outcome.status == NEW:
            self.metrics.inc("orders_amended")
            return pb2.AmendResponse(
                order_id=request.order_id, success=True,
                remaining_quantity=outcome.remaining,
            )
        return pb2.AmendResponse(
            order_id=request.order_id, success=False,
            error_message=outcome.error or "amend rejected",
        )

    def _amend_native(self, request, dispatcher=None):
        """AmendOrder tail on the lane path: lookup/ownership/reduction
        checks run natively; `new_quantity` rides the record's quantity
        field (me_lanes.cpp kOpAmend)."""
        from matching_engine_tpu.server.dispatcher import RingFull

        if dispatcher is None:
            dispatcher = self.dispatcher
        err = self._target_fits_record(request)
        if err is not None:
            return pb2.AmendResponse(
                order_id=request.order_id, success=False, error_message=err)
        try:
            outcome = self._wait(dispatcher.submit_record(
                3, quantity=request.new_quantity,
                order_id=request.order_id.encode(),
                client_id=request.client_id.encode(),
            ), dispatcher)
        except RingFull:
            return pb2.AmendResponse(
                order_id=request.order_id, success=False,
                error_message="server overloaded",
            )
        except Exception:  # noqa: BLE001
            return pb2.AmendResponse(
                order_id=request.order_id, success=False,
                error_message="engine error",
            )
        if outcome.ok:
            return pb2.AmendResponse(
                order_id=request.order_id, success=True,
                remaining_quantity=outcome.remaining,
            )
        return pb2.AmendResponse(
            order_id=request.order_id, success=False,
            error_message=outcome.error or "amend rejected",
        )

    # -- GetOrderBook ------------------------------------------------------

    def GetOrderBook(self, request, context):
        self.metrics.inc("rpc_book")
        if self._book_cache_s > 0.0:
            # Conflated latest-state snapshot (--book-cache-ms): a read
            # inside the TTL reuses the last materialized response and
            # never touches the runner's snapshot lock — which every
            # device step holds — so book-read bursts stop landing on
            # the dispatch tail. Staleness is bounded by the TTL; the
            # response proto is read-only after construction, so serving
            # one instance to concurrent readers is safe.
            now = time.monotonic()
            ent = self._book_cache.get(request.symbol)
            if ent is not None and now - ent[0] < self._book_cache_s:
                self.metrics.inc("book_cache_hits")
                return ent[1]
            self.metrics.inc("book_cache_misses")
            resp = self._build_book(request.symbol)
            runner, _ = self._lane_for_symbol(request.symbol)
            if runner.symbols.get(request.symbol) is None:
                # Unknown/empty symbol: serving it fresh is lock-free
                # and cheap (book_snapshot bails before the device), and
                # NOT caching it means a bogus-symbol flood can't churn
                # the hot legitimate entries out before their TTL.
                return resp
            # Re-insert at the dict TAIL (pop first — reassignment keeps
            # the original position, so a refreshed hot entry would sit
            # at the FIFO evictor's front forever), and stamp AFTER the
            # build: under snapshot-lock contention comparable to the
            # TTL, the pre-build stamp would insert entries already
            # near-expired.
            self._book_cache.pop(request.symbol, None)
            while len(self._book_cache) >= self._book_cache_cap:
                # Keyed by the CLIENT's symbol string, so bound it
                # against unknown-symbol request floods — evicting ONE
                # oldest-inserted entry per overflow (a clear-all would
                # let that same flood continuously wipe the hot
                # legitimate entries the cache exists to serve). Handler
                # threads race here unlocked: a concurrent evictor can
                # empty the dict between len() and next(), so treat an
                # exhausted/mutated iterator as someone else's eviction.
                try:
                    self._book_cache.pop(
                        next(iter(self._book_cache)), None)
                except (StopIteration, RuntimeError):
                    break
            self._book_cache[request.symbol] = (time.monotonic(), resp)
            return resp
        return self._build_book(request.symbol)

    def _build_book(self, symbol: str):
        runner, _ = self._lane_for_symbol(symbol)
        bids, asks = runner.book_snapshot(symbol)

        def msg(info, qty):
            return pb2.Order(
                order_id=info.order_id, client_id=info.client_id,
                price=info.price_q4, scale=4, quantity=qty, side=info.side,
            )

        def levels(rows):
            # rows arrive priority-sorted, so equal prices are adjacent —
            # one linear pass aggregates the L2 view in book order.
            out: list[pb2.Level] = []
            for info, qty in rows:
                if out and out[-1].price == info.price_q4:
                    out[-1].quantity += qty
                    out[-1].order_count += 1
                else:
                    out.append(pb2.Level(price=info.price_q4, quantity=qty,
                                         order_count=1))
            return out

        return pb2.OrderBookResponse(
            bids=[msg(i, q) for i, q in bids],
            asks=[msg(i, q) for i, q in asks],
            bid_levels=levels(bids),
            ask_levels=levels(asks),
        )

    # -- streams -----------------------------------------------------------

    def _stream_alive(self, context, sub):
        """Event-driven termination when the transport supports it: the
        gRPC context callback fires on client hangup and unsubscribe's
        sentinel wakes the blocked generator — no aliveness polling (idle
        subscriber threads sleep in get() instead of waking 4x/s).
        Returns the `alive` argument for sub.stream(): None (block) when
        the callback registered, else the context's poll (the native
        gateway's duck-typed context has no add_callback)."""
        register = getattr(context, "add_callback", None)
        if register is not None and register(
                lambda: self.hub.unsubscribe(sub)):
            return None
        return context.is_active

    # Replay slice per store round-trip: bounds the memory AND metric cost
    # of a gap-fill stream the client cancels early (feed.client takes
    # only its gap's range and hangs up — without chunking every fill
    # would materialize the store's full tail).
    _REPLAY_CHUNK = 1024

    def _sequenced_stream(self, sub, channel, key, resume_from,
                          resume_epoch, context, from_start=False):
        """Replay-then-live for the sequenced feed: the live subscription
        is already registered (events landing during the replay scan
        queue up in it), the retransmission store replays
        (resume_from, head] in chunks, and the live phase drops the
        overlap by seq. With the feed disabled (no sequencer)
        resume_from is ignored — the legacy live-only contract."""
        alive = self._stream_alive(context, sub)
        sequencer = self.hub.sequencer
        last = 0
        replay_epoch = 0
        # Replication bootstrap: an oplog subscriber with cursor 0 means
        # "from the beginning of this epoch" — a standby must see EVERY
        # retained record, so seq 0 grants a full (0, head] replay here
        # (on the md/ou/audit channels 0 keeps the legacy live-only
        # meaning — existing clients attach live by default).
        # Cursor 0 is a real from-the-epoch-start cursor here — also
        # when the client echoes the CURRENT epoch (a gap-fill for a
        # dropped first event sends resume_from_seq=0 with the learned
        # epoch; treating that as live-only would make the fill a
        # guaranteed no-op and falsely poison a standby whose missing
        # seqs are still retained). A MISMATCHED epoch keeps the stale-
        # cursor rebase semantics below.
        full = (resume_from == 0
                and (channel == CHANNEL_OPLOG or from_start)
                and (not resume_epoch
                     or (sequencer is not None
                         and resume_epoch == sequencer.epoch)))
        if sequencer is not None and (resume_from or full):
            stale = (resume_epoch and resume_epoch != sequencer.epoch)
            if not full and (
                    stale or resume_from > sequencer.last_seq(channel, key)):
                # Seq domains are per boot: a cursor from another epoch
                # (or ahead of the current head, for clients that never
                # learned an epoch) is stale — the server restarted.
                # Serve live from the new epoch instead of replaying a
                # DIFFERENT boot's range or filtering everything below
                # the stale cursor into silence; feed.client detects the
                # epoch change on the events and reports a rebase.
                self._log(f"feed resume {channel}/{key}: cursor "
                          f"{resume_from} is from "
                          f"{'epoch ' + str(resume_epoch) if stale else 'ahead of this boot'} "
                          f"(epoch rebase); serving live")
            else:
                last, missed_total = resume_from, 0
                replay_epoch = sequencer.epoch
                while True:
                    head = sequencer.last_seq(channel, key)
                    if last >= head:
                        break
                    to = min(head, last + self._REPLAY_CHUNK)
                    events, missed = sequencer.replay(channel, key, last,
                                                      to_seq=to)
                    missed_total += missed
                    for e in events:
                        yield e
                    # Advance past the chunk even when it was fully
                    # evicted — the client detects the hole and reports
                    # it unrecovered.
                    last = to
                if missed_total:
                    self._log(
                        f"feed replay {channel}/{key}: {missed_total} "
                        f"events past the retransmission window (client "
                        f"will report an unrecovered gap)")
        for e in sub.stream(alive=alive):
            if last and getattr(e, "seq", 0) and e.seq <= last \
                    and getattr(e, "feed_epoch", replay_epoch) == replay_epoch:
                # Replay/live overlap — SAME epoch only: an in-place
                # promotion rebase restarts the seq domain on this live
                # connection, and filtering the new epoch's first events
                # against the old epoch's replay cursor would silently
                # swallow them (the client's rebase detection never sees
                # a gap to account).
                continue
            yield e

    def StreamMarketData(self, request, context):
        self.metrics.inc("rpc_stream_md")
        sub = self.hub.subscribe_market_data(request.symbol,
                                             conflate=request.conflate)
        try:
            yield from self._sequenced_stream(
                sub, CHANNEL_MD, request.symbol, request.resume_from_seq,
                request.feed_epoch, context)
        finally:
            self.hub.unsubscribe(sub)

    def StreamOrderUpdates(self, request, context):
        from_start = False
        if request.client_id in (AUDIT_CLIENT, AUDIT_CLIENT_FULL):
            # Drop-copy tap: the reserved client id subscribes to the
            # venue-wide audit channel (lifecycle records for EVERY
            # order) — replay/resume/gap-fill work exactly like any
            # sequenced channel, same RPC surface. The _FULL variant
            # makes cursor 0 a REAL from-the-epoch-start cursor (full
            # retained replay) instead of the legacy live attach — the
            # standby attestor must cover the same replayed range its
            # applier consumes from the op log.
            self.metrics.inc("rpc_stream_audit")
            sub = self.hub.subscribe_audit()
            channel, key = CHANNEL_AUDIT, AUDIT_DOMAIN_KEY
            from_start = request.client_id == AUDIT_CLIENT_FULL
        elif request.client_id == OPLOG_CLIENT:
            # Replication tap: the op-log channel a warm standby applies
            # (replication/standby.py). Cursor 0 = full replay from the
            # epoch start; see _sequenced_stream.
            self.metrics.inc("rpc_stream_oplog")
            sub = self.hub.subscribe_oplog()
            channel, key = CHANNEL_OPLOG, OPLOG_DOMAIN_KEY
        else:
            self.metrics.inc("rpc_stream_ou")
            sub = self.hub.subscribe_order_updates(request.client_id)
            channel, key = CHANNEL_OU, request.client_id
        try:
            yield from self._sequenced_stream(
                sub, channel, key, request.resume_from_seq,
                request.feed_epoch, context, from_start=from_start)
        finally:
            self.hub.unsubscribe(sub)

    # -- metrics -----------------------------------------------------------

    def GetMetrics(self, request, context):
        counters, gauges = self.metrics.snapshot()
        return pb2.MetricsResponse(gauges=gauges, counters=counters)

    # -- replication --------------------------------------------------------

    def Promote(self, request, context):
        """Flip a --standby replica into the serving primary
        (replication/standby.py promote): feed-epoch bump, OID floor
        re-seed, mutation RPCs open. Application-level failure semantics
        match SubmitOrder — a non-standby server answers success=false."""
        self.metrics.inc("rpc_promote")
        if self.replica is None:
            return pb2.PromoteResponse(
                success=False,
                error_message="not a standby replica (no --standby)")
        self._log("Promote requested via RPC")
        epoch = self.replica.promote("rpc")
        if not epoch:
            # Two distinct falsy outcomes, and the operator mid-incident
            # must not confuse them: the winner ABORTED (wedged applier
            # — it poisoned the replica with the reason, and a retry
            # fails identically), or a concurrent promotion holds the
            # transition and outlived our wait (not promoted YET).
            poisoned = self.replica.poisoned
            if poisoned is not None:
                return pb2.PromoteResponse(
                    success=False,
                    error_message=f"promotion FAILED: {poisoned}")
            return pb2.PromoteResponse(
                success=False,
                error_message="promotion already in progress and still "
                              "quiescing; poll /replz for the verdict")
        return pb2.PromoteResponse(success=True, feed_epoch=epoch)

    # -- call auction ------------------------------------------------------

    def RunAuction(self, request, context):
        """Batch uncross (engine/auction.py): one symbol, or every symbol
        this host serves when request.symbol is empty. Failures are
        application-level (success=false + message, gRPC OK) — the
        SubmitOrder reject convention."""
        symbol = request.symbol or None
        if self.read_only:
            return pb2.AuctionResponse(success=False,
                                       error_message=self._STANDBY_ERR)
        if self.oplog_ship:
            return pb2.AuctionResponse(
                success=False,
                error_message="auction uncross is not replicated on the "
                              "op log: running it would silently diverge "
                              "every standby — drop --oplog-ship to run "
                              "auctions")
        if getattr(request, "open_call", False):
            # Scenario/workload replay hook: (re)open the venue-wide call
            # period without uncrossing — submits rest unmatched until a
            # later all-symbols RunAuction clears them. Mirrors
            # --auction-open's boot-time flip, now reachable mid-session
            # so recorded auction-day flow (open -> continuous -> halt ->
            # reopen -> close) replays through a live server.
            if symbol is not None:
                return pb2.AuctionResponse(
                    success=False,
                    error_message="a call period is venue-wide: open_call "
                                  "requires an empty symbol")
            target = self.shards if self.shards is not None else self.runner
            try:
                target.set_auction_mode(True)
            except ValueError as e:  # venue-depth capacity: no call periods
                return pb2.AuctionResponse(success=False,
                                           error_message=str(e))
            target.flush_auction_mode()
            self._log("auction call period OPEN (RunAuction open_call)")
            return pb2.AuctionResponse(success=True)
        if self.shards is not None:
            # Partitioned serving: one symbol touches only its owning
            # lane; the all-symbols close fans out across every lane and
            # merges the per-lane all-or-nothing summaries.
            self._log(f"auction {'ALL' if symbol is None else symbol} "
                      f"(across {self.shards.num_shards} lanes)")
            summary = self.shards.run_auction(
                [symbol] if symbol else None)
        else:
            if symbol is not None and not self.runner.owns_symbol(symbol):
                return pb2.AuctionResponse(
                    success=False,
                    error_message=f"symbol {symbol} is homed on another host",
                )
            self._log(f"auction {'ALL' if symbol is None else symbol}")
            summary = self.runner.run_auction(
                [symbol] if symbol else None, sink=self.dispatcher.sink)
        if summary["error"]:
            return pb2.AuctionResponse(success=False,
                                       error_message=summary["error"])
        crossed = summary["crossed"]
        total = sum(q for _, _, q in crossed)
        price = crossed[0][1] if symbol is not None and crossed else 0
        note = summary.get("warning", "")
        if symbol is not None and not crossed and not note:
            # Explicit no-cross signal (ADVICE r3): success=true with
            # clearing_price=0 x0 was indistinguishable from a
            # tiny-but-real clear; say so on the success channel.
            note = f"book for {symbol} did not cross; nothing executed"
        return pb2.AuctionResponse(
            success=True,
            # A mesh partial abort is a success with a warning: the
            # overflowing shard's symbols are untouched, the rest cleared.
            error_message=note,
            clearing_price=price,
            executed_quantity=total,
            symbols_crossed=len(crossed),
        )
