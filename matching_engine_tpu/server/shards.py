"""Partitioned serving: a symbol→shard router over K independent lanes.

The device kernel matches ~2B orders/s, but one dispatcher thread driving
one runner caps the serving stack at single-thread Python speed — and
nothing in the serving path could use more than one chip's dispatch lane
(MULTICHIP artifacts recorded no serving number at all). Books are
independent per symbol (the premise of the vmap'd struct-of-array
design), so the symbol space is cut into K disjoint shards, each owning
``num_symbols/K`` engine rows, the way CoinTossX shards its matching
across instruments:

    edge (grpcio / C++ gateway)
      └─ ShardRouter: symbol ──crc32──▶ shard  (cancels/amends route by
         the order id's strided residue, falling back to a directory
         probe for ids recovered from a different shard count)
            ├─ lane 0: ring → dispatcher thread → EngineRunner → device 0
            ├─ lane 1: ring → dispatcher thread → EngineRunner → device 1
            ⋮      (embarrassingly parallel: no locks, no collectives
            └─ lane K-1     between lanes on the hot path)

Every single-owner assumption in the single-lane stack becomes a
per-lane invariant; the explicit cross-lane aggregation points are:

- **Order IDs**: lane i allocates the strided residue class
  {i+1, i+1+K, ...} (EngineRunner.oid_offset/oid_stride; the C++ lane
  engine mirrors the stride), so "OID-<n>" stays globally unique with no
  cross-lane lock and ``(n-1) % K`` recovers the birth lane.
- **Streams/feed**: all lanes publish into ONE StreamHub/FeedSequencer —
  both are internally locked, and seq domains are per-(channel, key), so
  a client's order-update stream fans in across lanes with a gapless
  per-key seq line (tests/test_serve_shards.py proves it under
  concurrent lane publish).
- **Storage**: one shared sink; rows from all lanes serialize in its
  writer. The durable store is shard-agnostic (recovery re-routes rows
  by symbol), so a store written at any K restores at any other K.
- **Book views / auctions**: GetOrderBook routes to the one lane owning
  the symbol; an all-symbols RunAuction fans out to every lane and
  merges the per-lane summaries (per-lane all-or-nothing, mirroring the
  mesh path's per-shard abort semantics).
- **Checkpoints**: one CheckpointDaemon per lane under
  ``<root>/shard-<i>/`` (wired by build_server), restored per lane.

The ``ShardedEngine`` mesh path (parallel/sharding.py) is unchanged and
remains the market-wide-view/auction formulation; serving shards are the
host-parallel cut — with multiple visible devices each lane's books pin
to its own chip, so host parallelism and multi-chip serving fall out of
the same partition.

Known residual: STP owner ids are assigned per lane at first sight.
Deterministic hashing keeps lanes agreed except when two NEW
hash-colliding client ids first appear on different lanes in the same
boot — the collision counter fires and the persisted registry reconciles
at the next boot (all lanes preload it).
"""

from __future__ import annotations

import threading
import time

from matching_engine_tpu.parallel.multihost import symbol_home
from matching_engine_tpu.utils.metrics import Metrics


class ShardRouter:
    """Deterministic symbol→shard mapping (the same stable CRC32 hash as
    multi-host symbol homing, so a front-end router can compute it too).
    """

    __slots__ = ("num_shards",)

    def __init__(self, num_shards: int):
        if num_shards < 1:
            raise ValueError("num_shards must be >= 1")
        self.num_shards = num_shards

    def shard_of(self, symbol: str) -> int:
        return symbol_home(symbol, self.num_shards)

    def shard_of_order_id(self, order_id: str) -> int | None:
        """Birth lane of an id allocated under THIS shard count (strided
        residue); None for foreign/garbled ids — callers fall back to a
        directory probe (ids recovered from a store written at another
        shard count live on their symbol's lane, not their residue's)."""
        if not order_id.startswith("OID-"):
            return None
        try:
            n = int(order_id[4:])
        except ValueError:
            return None
        if n < 1:
            return None
        return (n - 1) % self.num_shards


class ServingLane:
    """One shard's serving column: runner + its dispatcher (+ optional
    checkpoint daemon, attached by build_server)."""

    __slots__ = ("shard_id", "runner", "dispatcher", "checkpointer")

    def __init__(self, shard_id: int, runner, dispatcher=None):
        self.shard_id = shard_id
        self.runner = runner
        self.dispatcher = dispatcher
        self.checkpointer = None

    def backlog(self) -> int:
        """Host-visible queue depth proxy for this lane: the submitted-
        but-uncompleted tag map on the native ring edges (their queue
        lives in C++), else the python dispatch queue."""
        d = self.dispatcher
        if d is None:
            return 0
        tags = getattr(d, "_tags", None)
        if tags is not None:
            return len(tags)
        q = getattr(d, "_q", None)
        return q.qsize() if q is not None and hasattr(q, "qsize") else 0


class ServingShards:
    """K serving lanes + the router + the cross-lane aggregation points.

    Lanes share ONE Metrics registry (counters aggregate naturally), ONE
    StreamHub/FeedSequencer (per-key fan-in), and ONE storage sink. The
    sampler thread publishes the per-lane balance picture:

    - ``lane<i>_queue_depth`` / ``lane<i>_ops_per_s`` — per-shard series
      (names carry the shard index; documented in OPERATIONS.md prose),
    - ``lane_queue_depth_max`` — worst backlog across lanes,
    - ``lane_dispatch_rate`` — summed lane throughput, orders/s,
    - ``lane_imbalance`` — max/mean of per-lane rates over the sample
      window (1.0 = perfectly balanced; K = all load on one lane).
    """

    def __init__(self, lanes: list[ServingLane], router: ShardRouter,
                 metrics: Metrics | None = None, sink=None,
                 sample_interval_s: float = 1.0):
        if len(lanes) != router.num_shards:
            raise ValueError("lane count != router shard count")
        self.lanes = lanes
        self.router = router
        self.metrics = metrics or lanes[0].runner.metrics
        self.sink = sink
        self._stop = threading.Event()
        self._sampler = None
        if sample_interval_s and sample_interval_s > 0:
            self._interval = sample_interval_s
            self._sampler = threading.Thread(
                target=self._sample_loop, name="lane-sampler", daemon=True)
            self._sampler.start()

    # -- routing -----------------------------------------------------------

    @property
    def num_shards(self) -> int:
        return self.router.num_shards

    def lane_for_symbol(self, symbol: str) -> ServingLane:
        return self.lanes[self.router.shard_of(symbol)]

    def lane_for_order(self, order_id: str) -> ServingLane:
        """Lane owning `order_id`: the strided-residue lane when its
        directory confirms the id, else a probe across the others (covers
        ids rebooted in from a different shard count — they live with
        their symbol). Unknown ids resolve to the residue lane (or lane
        0), whose dispatch answers "unknown order id" exactly as a
        single-lane server would."""
        first = self.router.shard_of_order_id(order_id)
        order = ([first] if first is not None else []) + [
            i for i in range(len(self.lanes)) if i != first]
        for i in order:
            if self._lane_knows(self.lanes[i], order_id):
                return self.lanes[i]
        return self.lanes[first if first is not None else 0]

    @staticmethod
    def _lane_knows(lane: ServingLane, order_id: str) -> bool:
        r = lane.runner
        if getattr(r, "native_lanes", False):
            return bool(r.lanes.lookup(order_id))
        return order_id in r.orders_by_id

    # -- cross-lane control plane ------------------------------------------

    @property
    def auction_mode(self) -> bool:
        return any(l.runner.auction_mode for l in self.lanes)

    def set_auction_mode(self, value: bool) -> None:
        for lane in self.lanes:
            lane.runner.set_auction_mode(value)

    def flush_auction_mode(self) -> None:
        for lane in self.lanes:
            lane.runner.flush_auction_mode()

    def flush_owner_ids(self) -> None:
        for lane in self.lanes:
            lane.runner.flush_owner_ids()

    def crossed_symbols(self) -> list[str]:
        out: list[str] = []
        for lane in self.lanes:
            out.extend(lane.runner.crossed_symbols())
        return out

    def run_auction(self, symbols=None, sink=None) -> dict:
        """Auction across lanes. With `symbols` the uncross touches only
        the lanes owning them; None = every lane (the all-symbols call-
        period close). Lanes run sequentially — each uncross holds only
        its own lane's dispatch lock — and the per-lane summaries merge
        with per-lane all-or-nothing semantics (a lane that aborts keeps
        its books untouched and, if open, its call period; the merged
        request fails only when EVERY touched lane failed)."""
        sink = sink if sink is not None else self.sink
        if symbols:
            by_lane: dict[int, list[str]] = {}
            for s in symbols:
                by_lane.setdefault(self.router.shard_of(s), []).append(s)
            work = [(self.lanes[i], syms) for i, syms in by_lane.items()]
        else:
            work = [(lane, None) for lane in self.lanes]
        crossed: list = []
        warnings: list[str] = []
        errors: list[str] = []
        aborted = False
        for lane, syms in work:
            summary = lane.runner.run_auction(syms, sink=sink)
            crossed.extend(summary["crossed"])
            aborted = aborted or summary["aborted"]
            if summary["error"]:
                errors.append(f"lane {lane.shard_id}: {summary['error']}")
            if summary.get("warning"):
                warnings.append(f"lane {lane.shard_id}: {summary['warning']}")
        if errors and len(errors) == len(work) and not crossed:
            return {"crossed": [], "aborted": aborted,
                    "error": "; ".join(errors), "warning": ""}
        warnings.extend(errors)  # partial failure: success with a warning
        return {"crossed": crossed, "aborted": aborted, "error": "",
                "warning": "; ".join(w for w in warnings if w)}

    # -- lifecycle ---------------------------------------------------------

    def finish_pending(self) -> None:
        for lane in self.lanes:
            lane.runner.finish_pending()

    def close(self) -> None:
        self._stop.set()
        for lane in self.lanes:
            if lane.dispatcher is not None:
                lane.dispatcher.close()
        if self._sampler is not None:
            self._sampler.join(timeout=5)

    # -- the balance sampler -----------------------------------------------

    def _sample_loop(self) -> None:
        last_ops = [lane.runner.ops_dispatched for lane in self.lanes]
        last_t = time.perf_counter()
        while not self._stop.wait(self._interval):
            last_ops, last_t = self._sample_once(last_ops, last_t)

    def _sample_once(self, last_ops, last_t):
        """One sampler tick (split out for tests): publish per-lane depth
        and rate plus the cross-lane aggregates."""
        now = time.perf_counter()
        dt = max(1e-9, now - last_t)
        ops = [lane.runner.ops_dispatched for lane in self.lanes]
        rates = [(o - lo) / dt for o, lo in zip(ops, last_ops)]
        depths = [lane.backlog() for lane in self.lanes]
        m = self.metrics
        for i, (d, r) in enumerate(zip(depths, rates)):
            m.set_gauge(f"lane{i}_queue_depth", d)
            m.set_gauge(f"lane{i}_ops_per_s", r)
        m.set_gauge("lane_queue_depth_max", max(depths))
        total = sum(rates)
        m.set_gauge("lane_dispatch_rate", total)
        mean = total / len(rates)
        m.set_gauge("lane_imbalance", max(rates) / mean if mean > 0 else 1.0)
        return ops, now


def make_lane_runner(cfg, router: ShardRouter, shard_id: int, *,
                     metrics=None, hub=None, pipeline_inflight: int = 2,
                     native_lanes: bool = False, devices=None,
                     megadispatch_max_waves: int = 1, tier_pins=None):
    """One lane's runner over a K-way split of `cfg`: the shard gets
    ``cfg.num_symbols // K`` engine rows, the strided OID residue class
    `shard_id`, the shard-ownership filter, and — when more than one
    device is visible — its own device (round-robin).

    A tiered `cfg` (cfg.tiers, --book-tiers) splits PROPORTIONALLY: every
    tier group's symbol count must divide by K, each lane gets the same
    spec at 1/K scale, and the whole pin map passes through (a lane only
    ever allocates symbols its owns_filter admits, so foreign pins are
    inert). Tiers route dispatches to the owning tier group inside each
    lane exactly like the router routes symbols to lanes."""
    import dataclasses

    import jax

    from matching_engine_tpu.server.engine_runner import EngineRunner

    k = router.num_shards
    if cfg.num_symbols % k != 0:
        raise ValueError(
            f"num_symbols {cfg.num_symbols} not divisible by "
            f"serve-shards {k}")
    lane_tiers = ()
    if cfg.tiers:
        if native_lanes:
            raise ValueError("--book-tiers does not compose with "
                             "--native-lanes")
        for n, cap in cfg.tiers:
            if n % k != 0:
                raise ValueError(
                    f"tier group {n}x{cap} not divisible by "
                    f"serve-shards {k} (every tier splits per lane)")
        lane_tiers = tuple((n // k, cap) for n, cap in cfg.tiers)
    shard_cfg = dataclasses.replace(cfg, num_symbols=cfg.num_symbols // k,
                                    tiers=lane_tiers)
    devices = devices if devices is not None else jax.devices()
    device = devices[shard_id % len(devices)] if len(devices) > 1 else None
    owns = (lambda s, _i=shard_id: router.shard_of(s) == _i)
    kwargs = {}
    cls = EngineRunner
    if native_lanes:
        from matching_engine_tpu.server.native_lanes import NativeLanesRunner

        cls = NativeLanesRunner
    elif cfg.tiers:
        from matching_engine_tpu.server.tiered_runner import (
            TieredEngineRunner,
        )

        cls = TieredEngineRunner
        kwargs["tier_pins"] = tier_pins
    return cls(shard_cfg, metrics, hub=hub,
               pipeline_inflight=pipeline_inflight,
               oid_offset=shard_id, oid_stride=k, device=device,
               owns_filter=owns,
               megadispatch_max_waves=megadispatch_max_waves, **kwargs)


def make_lane_dispatcher(runner, *, sink=None, hub=None,
                         window_ms: float = 2.0, metrics=None,
                         native: bool = False, native_lanes: bool = False,
                         mega_max_waves: int = 1,
                         mega_latency_us: float = 5000.0,
                         busy_poll_us: float = 0.0,
                         dropcopy=None, oplog=None, lane_id: int = 0):
    """One lane's dispatcher (its own ring + drain thread). Each lane
    runs its own megadispatch coalescing controller over its own queue
    (the decision is a per-lane queue-depth function; a venue-wide M
    would couple lanes the partition exists to decouple). busy_poll_us
    spins each lane's own drain — mind the core budget: K spinning lanes
    want K cores."""
    from matching_engine_tpu.server.dispatcher import (
        BatchDispatcher,
        LaneRingDispatcher,
        NativeRingDispatcher,
    )

    if native_lanes:
        return LaneRingDispatcher(runner, sink=sink, hub=hub,
                                  window_ms=window_ms, metrics=metrics,
                                  busy_poll_us=busy_poll_us,
                                  mega_max_waves=mega_max_waves,
                                  dropcopy=dropcopy)
    if native:
        return NativeRingDispatcher(runner, sink=sink, hub=hub,
                                    window_ms=window_ms, metrics=metrics,
                                    mega_max_waves=mega_max_waves,
                                    mega_latency_us=mega_latency_us,
                                    busy_poll_us=busy_poll_us,
                                    dropcopy=dropcopy, oplog=oplog,
                                    lane_id=lane_id)
    return BatchDispatcher(runner, sink=sink, hub=hub, window_ms=window_ms,
                           metrics=metrics, mega_max_waves=mega_max_waves,
                           mega_latency_us=mega_latency_us,
                           busy_poll_us=busy_poll_us, dropcopy=dropcopy,
                           oplog=oplog, lane_id=lane_id)


def build_serving_shards(
    cfg,
    num_shards: int,
    *,
    metrics: Metrics | None = None,
    hub=None,
    sink=None,
    window_ms: float = 2.0,
    pipeline_inflight: int = 2,
    native: bool = False,
    native_lanes: bool = False,
    with_dispatchers: bool = True,
    sample_interval_s: float = 1.0,
    megadispatch_max_waves: int = 1,
    megadispatch_latency_us: float = 5000.0,
    tier_pins=None,
) -> ServingShards:
    """Wire K (runner → dispatcher) lanes over a K-way split of `cfg`.

    All lanes share `metrics`, `hub` and `sink`. With `with_dispatchers`
    False the caller drives dispatch itself (benches/tests)."""
    metrics = metrics or Metrics()
    router = ShardRouter(num_shards)
    lanes: list[ServingLane] = []
    for i in range(num_shards):
        runner = make_lane_runner(
            cfg, router, i, metrics=metrics, hub=hub,
            pipeline_inflight=pipeline_inflight, native_lanes=native_lanes,
            megadispatch_max_waves=megadispatch_max_waves,
            tier_pins=tier_pins)
        dispatcher = None
        if with_dispatchers:
            dispatcher = make_lane_dispatcher(
                runner, sink=sink, hub=hub, window_ms=window_ms,
                metrics=metrics, native=native, native_lanes=native_lanes,
                mega_max_waves=megadispatch_max_waves,
                mega_latency_us=megadispatch_latency_us)
        lanes.append(ServingLane(i, runner, dispatcher))
    return ServingShards(lanes, router, metrics=metrics, sink=sink,
                         sample_interval_s=sample_interval_s)
