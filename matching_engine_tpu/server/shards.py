"""Partitioned serving: a symbol→shard router over K independent lanes.

The device kernel matches ~2B orders/s, but one dispatcher thread driving
one runner caps the serving stack at single-thread Python speed — and
nothing in the serving path could use more than one chip's dispatch lane
(MULTICHIP artifacts recorded no serving number at all). Books are
independent per symbol (the premise of the vmap'd struct-of-array
design), so the symbol space is cut into K disjoint shards, each owning
``num_symbols/K`` engine rows, the way CoinTossX shards its matching
across instruments:

    edge (grpcio / C++ gateway)
      └─ ShardRouter: symbol ──crc32──▶ shard  (cancels/amends route by
         the order id's strided residue, falling back to a directory
         probe for ids recovered from a different shard count)
            ├─ lane 0: ring → dispatcher thread → EngineRunner → device 0
            ├─ lane 1: ring → dispatcher thread → EngineRunner → device 1
            ⋮      (embarrassingly parallel: no locks, no collectives
            └─ lane K-1     between lanes on the hot path)

Every single-owner assumption in the single-lane stack becomes a
per-lane invariant; the explicit cross-lane aggregation points are:

- **Order IDs**: lane i allocates the strided residue class
  {i+1, i+1+K, ...} (EngineRunner.oid_offset/oid_stride; the C++ lane
  engine mirrors the stride), so "OID-<n>" stays globally unique with no
  cross-lane lock and ``(n-1) % K`` recovers the birth lane.
- **Streams/feed**: all lanes publish into ONE StreamHub/FeedSequencer —
  both are internally locked, and seq domains are per-(channel, key), so
  a client's order-update stream fans in across lanes with a gapless
  per-key seq line (tests/test_serve_shards.py proves it under
  concurrent lane publish).
- **Storage**: one shared sink; rows from all lanes serialize in its
  writer. The durable store is shard-agnostic (recovery re-routes rows
  by symbol), so a store written at any K restores at any other K.
- **Book views / auctions**: GetOrderBook routes to the one lane owning
  the symbol; symbol-targeted RunAuctions run per owning lane (per-lane
  all-or-nothing, mirroring the mesh path's per-shard abort semantics),
  while the all-symbols call-period close runs a TWO-PHASE barrier —
  every lane quiesces, snapshots books, prepares its device uncross,
  and only a unanimous vote commits; any lane failure rolls every lane
  back bit-identically (_AuctionBarrier + EngineRunner's phased hooks).
- **Checkpoints**: one CheckpointDaemon per lane under
  ``<root>/shard-<i>/`` (wired by build_server), restored per lane.

The ``ShardedEngine`` mesh path (parallel/sharding.py) is unchanged and
remains the market-wide-view/auction formulation; serving shards are the
host-parallel cut — with multiple visible devices each lane's books pin
to its own chip, so host parallelism and multi-chip serving fall out of
the same partition.

Known residual: STP owner ids are assigned per lane at first sight.
Deterministic hashing keeps lanes agreed except when two NEW
hash-colliding client ids first appear on different lanes in the same
boot — the collision counter fires and the persisted registry reconciles
at the next boot (all lanes preload it).
"""

from __future__ import annotations

import threading
import time

from matching_engine_tpu.parallel.multihost import symbol_home
from matching_engine_tpu.utils.metrics import Metrics

# Sentinel for make_lane_runner's `device` parameter: "not passed" must
# stay distinct from an explicit None (= jax default placement).
_AUTO = object()


def parse_shard_devices(spec, num_shards: int, devices=None) -> list:
    """Resolve a ``--shard-devices`` placement spec into one device per
    lane (None = jax default placement, no device_put):

    - ``auto`` (or empty): round-robin across all visible devices when
      more than one is visible; default placement on single-device boxes
      (skips the boot-time device_put a 1-device round-robin would pay).
    - ``roundrobin``: ALWAYS explicit — lane i commits its books and jit
      executables to ``devices[i % len(devices)]``, even with one device.
    - ``pinned:<o0,o1,...>``: one device ordinal per lane, exactly
      ``num_shards`` of them (e.g. ``pinned:0,0,1,1`` packs lane pairs).

    Raises ValueError (a boot CONFIG-ERROR) on malformed specs, ordinal
    counts that don't match the lane count, or out-of-range ordinals."""
    import jax

    devices = list(devices) if devices is not None else list(jax.devices())
    spec = (spec or "auto").strip()
    if spec == "auto":
        if len(devices) > 1:
            return [devices[i % len(devices)] for i in range(num_shards)]
        return [None] * num_shards
    if spec == "roundrobin":
        return [devices[i % len(devices)] for i in range(num_shards)]
    if spec.startswith("pinned:"):
        body = spec[len("pinned:"):]
        try:
            ordinals = [int(x) for x in body.split(",")] if body else []
        except ValueError:
            raise ValueError(
                f"--shard-devices pinned spec {body!r}: ordinals must be "
                f"comma-separated integers")
        if len(ordinals) != num_shards:
            raise ValueError(
                f"--shard-devices pinned:{body} names {len(ordinals)} "
                f"lane(s); --serve-shards is {num_shards} (give exactly "
                f"one device ordinal per lane)")
        bad = sorted({o for o in ordinals if not 0 <= o < len(devices)})
        if bad:
            raise ValueError(
                f"--shard-devices ordinal(s) {bad} out of range: "
                f"{len(devices)} visible device(s) "
                f"(valid: 0..{len(devices) - 1})")
        return [devices[o] for o in ordinals]
    raise ValueError(
        f"--shard-devices {spec!r}: expected auto | roundrobin | "
        f"pinned:<o0,o1,...>")


class ShardRouter:
    """Deterministic symbol→shard mapping (the same stable CRC32 hash as
    multi-host symbol homing, so a front-end router can compute it too).
    """

    __slots__ = ("num_shards",)

    def __init__(self, num_shards: int):
        if num_shards < 1:
            raise ValueError("num_shards must be >= 1")
        self.num_shards = num_shards

    def shard_of(self, symbol: str) -> int:
        return symbol_home(symbol, self.num_shards)

    def shard_of_order_id(self, order_id: str) -> int | None:
        """Birth lane of an id allocated under THIS shard count (strided
        residue); None for foreign/garbled ids — callers fall back to a
        directory probe (ids recovered from a store written at another
        shard count live on their symbol's lane, not their residue's)."""
        if not order_id.startswith("OID-"):
            return None
        try:
            n = int(order_id[4:])
        except ValueError:
            return None
        if n < 1:
            return None
        return (n - 1) % self.num_shards


class ServingLane:
    """One shard's serving column: runner + its dispatcher (+ optional
    checkpoint daemon, attached by build_server)."""

    __slots__ = ("shard_id", "runner", "dispatcher", "checkpointer")

    def __init__(self, shard_id: int, runner, dispatcher=None):
        self.shard_id = shard_id
        self.runner = runner
        self.dispatcher = dispatcher
        self.checkpointer = None

    def backlog(self) -> int:
        """Host-visible queue depth proxy for this lane: the submitted-
        but-uncompleted tag map on the native ring edges (their queue
        lives in C++), else the python dispatch queue."""
        d = self.dispatcher
        if d is None:
            return 0
        tags = getattr(d, "_tags", None)
        if tags is not None:
            return len(tags)
        q = getattr(d, "_q", None)
        return q.qsize() if q is not None and hasattr(q, "qsize") else 0


class _AuctionBarrier:
    """Two-phase commit vote for the cross-lane all-symbols uncross.

    Each lane worker, having PREPARED its uncross (device step done,
    host directories untouched, pre-auction books snapshotted), calls
    vote_and_wait: the call blocks until every lane has voted — or any
    lane votes abort, or the decision timeout lapses — and returns the
    collective decision. True (commit) only when ALL K lanes voted ok.
    An abort seals the decision immediately (healthy lanes are released
    rather than held for stragglers); a lane that times out waiting
    seals abort itself, so a wedged peer can never leave the venue
    half-uncrossed — the wedged lane, when it finally votes, reads the
    sealed abort and rolls its snapshot back."""

    def __init__(self, n: int, timeout_s: float = 60.0):
        self._lock = threading.Lock()
        self._decided = threading.Event()
        self._n = n
        self._timeout_s = timeout_s
        self._votes = 0
        self._ok = True
        self.committed = False
        self.reasons: list[str] = []

    def vote_and_wait(self, ok: bool, reason: str = "") -> bool:
        with self._lock:
            self._votes += 1
            if not ok:
                self._ok = False
                if reason:
                    self.reasons.append(reason)
            if not self._ok or self._votes == self._n:
                self.committed = self._ok and self._votes == self._n
                self._decided.set()
        if not self._decided.wait(self._timeout_s):
            with self._lock:
                if not self._decided.is_set():
                    self._ok = False
                    self.committed = False
                    self.reasons.append(
                        f"barrier decision timeout after "
                        f"{self._timeout_s:.0f}s")
                    self._decided.set()
        with self._lock:
            return self.committed

    def outcome(self) -> tuple[bool, list[str]]:
        """The sealed decision, read under the barrier lock (the
        worker joins already order these reads; the lock makes the
        rendezvous visible to the lockset analyzer too)."""
        with self._lock:
            return self.committed, list(self.reasons)


class ServingShards:
    """K serving lanes + the router + the cross-lane aggregation points.

    Lanes share ONE Metrics registry (counters aggregate naturally), ONE
    StreamHub/FeedSequencer (per-key fan-in), and ONE storage sink. The
    sampler thread publishes the per-lane balance picture:

    - ``lane<i>_queue_depth`` / ``lane<i>_ops_per_s`` — per-shard series
      (names carry the shard index; documented in OPERATIONS.md prose),
    - ``lane_queue_depth_max`` — worst backlog across lanes,
    - ``lane_dispatch_rate`` — summed lane throughput, orders/s,
    - ``lane_imbalance`` — max/mean of per-lane rates over the sample
      window (1.0 = perfectly balanced; K = all load on one lane).
    """

    def __init__(self, lanes: list[ServingLane], router: ShardRouter,
                 metrics: Metrics | None = None, sink=None,
                 sample_interval_s: float = 1.0):
        if len(lanes) != router.num_shards:
            raise ValueError("lane count != router shard count")
        self.lanes = lanes
        self.router = router
        self.metrics = metrics or lanes[0].runner.metrics
        self.sink = sink
        self._stop = threading.Event()
        self._sampler = None
        if sample_interval_s and sample_interval_s > 0:
            self._interval = sample_interval_s
            self._sampler = threading.Thread(
                target=self._sample_loop, name="lane-sampler", daemon=True)
            self._sampler.start()

    # -- routing -----------------------------------------------------------

    @property
    def num_shards(self) -> int:
        return self.router.num_shards

    def lane_for_symbol(self, symbol: str) -> ServingLane:
        return self.lanes[self.router.shard_of(symbol)]

    def lane_for_order(self, order_id: str) -> ServingLane:
        """Lane owning `order_id`: the strided-residue lane when its
        directory confirms the id, else a probe across the others (covers
        ids rebooted in from a different shard count — they live with
        their symbol). Unknown ids resolve to the residue lane (or lane
        0), whose dispatch answers "unknown order id" exactly as a
        single-lane server would."""
        first = self.router.shard_of_order_id(order_id)
        order = ([first] if first is not None else []) + [
            i for i in range(len(self.lanes)) if i != first]
        for i in order:
            if self._lane_knows(self.lanes[i], order_id):
                return self.lanes[i]
        return self.lanes[first if first is not None else 0]

    @staticmethod
    def _lane_knows(lane: ServingLane, order_id: str) -> bool:
        r = lane.runner
        if getattr(r, "native_lanes", False):
            return bool(r.lanes.lookup(order_id))
        return order_id in r.orders_by_id

    # -- cross-lane control plane ------------------------------------------

    @property
    def auction_mode(self) -> bool:
        return any(l.runner.auction_mode for l in self.lanes)

    def set_auction_mode(self, value: bool) -> None:
        for lane in self.lanes:
            lane.runner.set_auction_mode(value)

    def flush_auction_mode(self) -> None:
        for lane in self.lanes:
            lane.runner.flush_auction_mode()

    def flush_owner_ids(self) -> None:
        for lane in self.lanes:
            lane.runner.flush_owner_ids()

    def crossed_symbols(self) -> list[str]:
        out: list[str] = []
        for lane in self.lanes:
            out.extend(lane.runner.crossed_symbols())
        return out

    def run_auction(self, symbols=None, sink=None) -> dict:
        """Auction across lanes. With `symbols` the uncross touches only
        the lanes owning them, sequentially, with per-lane all-or-nothing
        semantics (a lane that aborts keeps its books untouched and, if
        open, its call period; the merged request fails only when EVERY
        touched lane failed). None/empty = the all-symbols call-period
        close: with K > 1 lanes that runs through a two-phase
        quiesce/commit BARRIER so every lane uncrosses at one consistent
        venue point, all-or-nothing ACROSS lanes — any lane failing to
        prepare rolls every lane back bit-identically."""
        sink = sink if sink is not None else self.sink
        if not symbols and len(self.lanes) > 1:
            return self._run_auction_barrier(sink)
        if symbols:
            by_lane: dict[int, list[str]] = {}
            for s in symbols:
                by_lane.setdefault(self.router.shard_of(s), []).append(s)
            work = [(self.lanes[i], syms) for i, syms in by_lane.items()]
        else:
            work = [(lane, None) for lane in self.lanes]
        crossed: list = []
        warnings: list[str] = []
        errors: list[str] = []
        aborted = False
        for lane, syms in work:
            summary = lane.runner.run_auction(syms, sink=sink)
            crossed.extend(summary["crossed"])
            aborted = aborted or summary["aborted"]
            if summary["error"]:
                errors.append(f"lane {lane.shard_id}: {summary['error']}")
            if summary.get("warning"):
                warnings.append(f"lane {lane.shard_id}: {summary['warning']}")
        if errors and len(errors) == len(work) and not crossed:
            return {"crossed": [], "aborted": aborted,
                    "error": "; ".join(errors), "warning": ""}
        warnings.extend(errors)  # partial failure: success with a warning
        return {"crossed": crossed, "aborted": aborted, "error": "",
                "warning": "; ".join(w for w in warnings if w)}

    def _run_auction_barrier(self, sink) -> dict:
        """All-symbols uncross across K > 1 lanes at ONE consistent venue
        point: one worker per lane quiesces its dispatcher, snapshots its
        books, runs the device uncross (prepare), then votes into a
        two-phase barrier. Only a unanimous vote commits — any lane
        failure (prepare error, exception, wedge) aborts EVERY lane,
        restoring each snapshot so the venue is bit-identical to never
        having auctioned. Each worker holds only its own lane's dispatch
        lock; the barrier's internal lock is the only cross-lane point,
        so no lock-order cycle is possible."""
        barrier = _AuctionBarrier(len(self.lanes))
        results: list = [None] * len(self.lanes)
        workers = [
            threading.Thread(
                target=self._barrier_lane,
                args=(lane, sink, barrier, results),
                name=f"auction-barrier-{lane.shard_id}", daemon=True)
            for lane in self.lanes
        ]
        for w in workers:
            w.start()
        for w in workers:
            w.join()
        committed, reasons = barrier.outcome()
        if not committed:
            self.metrics.inc("auction_barrier_aborts")
            return {"crossed": [], "aborted": True,
                    "error": "cross-lane auction barrier aborted: "
                             + ("; ".join(reasons) or "lane failure"),
                    "warning": ""}
        self.metrics.inc("auction_barrier_commits")
        crossed: list = []
        warnings: list[str] = []
        aborted = False
        for summary in results:
            if summary is None:
                continue
            crossed.extend(summary["crossed"])
            aborted = aborted or summary["aborted"]
            if summary.get("warning"):
                warnings.append(summary["warning"])
        return {"crossed": crossed, "aborted": aborted, "error": "",
                "warning": "; ".join(w for w in warnings if w)}

    def _barrier_lane(self, lane, sink, barrier, results) -> None:
        """Barrier worker (declared thread role "auction_barrier"):
        drives ONE lane's run_auction_phased, voting the lane's prepare
        outcome and abiding by the collective decision."""
        runner = lane.runner

        def decide(ok: bool, err: str) -> bool:
            return barrier.vote_and_wait(
                ok, f"lane {lane.shard_id}: {err}" if err else "")

        try:
            results[lane.shard_id] = runner.run_auction_phased(
                decide, sink=sink)
        except Exception as e:
            # run_auction_phased voted abort before re-raising, so peers
            # are already released; surface the failure in the merge.
            results[lane.shard_id] = {
                "crossed": [], "aborted": True,
                "error": f"{type(e).__name__}: {e}", "warning": ""}

    # -- lifecycle ---------------------------------------------------------

    def finish_pending(self) -> None:
        for lane in self.lanes:
            lane.runner.finish_pending()

    def close(self) -> None:
        self._stop.set()
        for lane in self.lanes:
            if lane.dispatcher is not None:
                lane.dispatcher.close()
        if self._sampler is not None:
            self._sampler.join(timeout=5)

    # -- the balance sampler -----------------------------------------------

    def _sample_loop(self) -> None:
        last_ops = [lane.runner.ops_dispatched for lane in self.lanes]
        last_t = time.perf_counter()
        while not self._stop.wait(self._interval):
            last_ops, last_t = self._sample_once(last_ops, last_t)

    def _sample_once(self, last_ops, last_t):
        """One sampler tick (split out for tests): publish per-lane depth
        and rate plus the cross-lane aggregates."""
        now = time.perf_counter()
        dt = max(1e-9, now - last_t)
        ops = [lane.runner.ops_dispatched for lane in self.lanes]
        rates = [(o - lo) / dt for o, lo in zip(ops, last_ops)]
        depths = [lane.backlog() for lane in self.lanes]
        m = self.metrics
        for i, (d, r) in enumerate(zip(depths, rates)):
            m.set_gauge(f"lane{i}_queue_depth", d)
            m.set_gauge(f"lane{i}_ops_per_s", r)
        m.set_gauge("lane_queue_depth_max", max(depths))
        total = sum(rates)
        m.set_gauge("lane_dispatch_rate", total)
        mean = total / len(rates)
        m.set_gauge("lane_imbalance", max(rates) / mean if mean > 0 else 1.0)
        # Placement identity + per-device aggregates: the imbalance gauge
        # is only ACTIONABLE when attributable to placement — lane<i>_device
        # pins each lane to its device ordinal, device<d>_ops_per_s sums
        # the lanes each device carries.
        by_dev: dict[int, float] = {}
        for i, lane in enumerate(self.lanes):
            dev = getattr(lane.runner, "device", None)
            did = int(getattr(dev, "id", 0)) if dev is not None else 0
            m.set_gauge(f"lane{i}_device", did)
            by_dev[did] = by_dev.get(did, 0.0) + rates[i]
        for did in sorted(by_dev):
            m.set_gauge(f"device{did}_ops_per_s", by_dev[did])
        return ops, now


def make_lane_runner(cfg, router: ShardRouter, shard_id: int, *,
                     metrics=None, hub=None, pipeline_inflight: int = 2,
                     native_lanes: bool = False, devices=None,
                     device=_AUTO,
                     megadispatch_max_waves: int = 1, tier_pins=None):
    """One lane's runner over a K-way split of `cfg`: the shard gets
    ``cfg.num_symbols // K`` engine rows, the strided OID residue class
    `shard_id`, the shard-ownership filter, and its device: pass
    `device` explicitly (from parse_shard_devices; None = jax default
    placement) or leave it unset for the auto policy — round-robin when
    more than one device is visible.

    A tiered `cfg` (cfg.tiers, --book-tiers) splits PROPORTIONALLY: every
    tier group's symbol count must divide by K, each lane gets the same
    spec at 1/K scale, and the whole pin map passes through (a lane only
    ever allocates symbols its owns_filter admits, so foreign pins are
    inert). Tiers route dispatches to the owning tier group inside each
    lane exactly like the router routes symbols to lanes."""
    import dataclasses

    import jax

    from matching_engine_tpu.server.engine_runner import EngineRunner

    k = router.num_shards
    if cfg.num_symbols % k != 0:
        raise ValueError(
            f"num_symbols {cfg.num_symbols} not divisible by "
            f"serve-shards {k}")
    lane_tiers = ()
    if cfg.tiers:
        if native_lanes:
            raise ValueError("--book-tiers does not compose with "
                             "--native-lanes")
        for n, cap in cfg.tiers:
            if n % k != 0:
                raise ValueError(
                    f"tier group {n}x{cap} not divisible by "
                    f"serve-shards {k} (every tier splits per lane)")
        lane_tiers = tuple((n // k, cap) for n, cap in cfg.tiers)
    shard_cfg = dataclasses.replace(cfg, num_symbols=cfg.num_symbols // k,
                                    tiers=lane_tiers)
    if device is _AUTO:
        devices = devices if devices is not None else jax.devices()
        device = (devices[shard_id % len(devices)]
                  if len(devices) > 1 else None)
    owns = (lambda s, _i=shard_id: router.shard_of(s) == _i)
    kwargs = {}
    cls = EngineRunner
    if native_lanes:
        from matching_engine_tpu.server.native_lanes import NativeLanesRunner

        cls = NativeLanesRunner
    elif cfg.tiers:
        from matching_engine_tpu.server.tiered_runner import (
            TieredEngineRunner,
        )

        cls = TieredEngineRunner
        kwargs["tier_pins"] = tier_pins
    return cls(shard_cfg, metrics, hub=hub,
               pipeline_inflight=pipeline_inflight,
               oid_offset=shard_id, oid_stride=k, device=device,
               owns_filter=owns,
               megadispatch_max_waves=megadispatch_max_waves, **kwargs)


def make_lane_dispatcher(runner, *, sink=None, hub=None,
                         window_ms: float = 2.0, metrics=None,
                         native: bool = False, native_lanes: bool = False,
                         mega_max_waves: int = 1,
                         mega_latency_us: float = 5000.0,
                         busy_poll_us: float = 0.0,
                         dropcopy=None, oplog=None, lane_id: int = 0):
    """One lane's dispatcher (its own ring + drain thread). Each lane
    runs its own megadispatch coalescing controller over its own queue
    (the decision is a per-lane queue-depth function; a venue-wide M
    would couple lanes the partition exists to decouple). busy_poll_us
    spins each lane's own drain — mind the core budget: K spinning lanes
    want K cores."""
    from matching_engine_tpu.server.dispatcher import (
        BatchDispatcher,
        LaneRingDispatcher,
        NativeRingDispatcher,
    )

    if native_lanes:
        return LaneRingDispatcher(runner, sink=sink, hub=hub,
                                  window_ms=window_ms, metrics=metrics,
                                  busy_poll_us=busy_poll_us,
                                  mega_max_waves=mega_max_waves,
                                  dropcopy=dropcopy)
    if native:
        return NativeRingDispatcher(runner, sink=sink, hub=hub,
                                    window_ms=window_ms, metrics=metrics,
                                    mega_max_waves=mega_max_waves,
                                    mega_latency_us=mega_latency_us,
                                    busy_poll_us=busy_poll_us,
                                    dropcopy=dropcopy, oplog=oplog,
                                    lane_id=lane_id)
    return BatchDispatcher(runner, sink=sink, hub=hub, window_ms=window_ms,
                           metrics=metrics, mega_max_waves=mega_max_waves,
                           mega_latency_us=mega_latency_us,
                           busy_poll_us=busy_poll_us, dropcopy=dropcopy,
                           oplog=oplog, lane_id=lane_id)


def build_serving_shards(
    cfg,
    num_shards: int,
    *,
    metrics: Metrics | None = None,
    hub=None,
    sink=None,
    window_ms: float = 2.0,
    pipeline_inflight: int = 2,
    native: bool = False,
    native_lanes: bool = False,
    with_dispatchers: bool = True,
    sample_interval_s: float = 1.0,
    megadispatch_max_waves: int = 1,
    megadispatch_latency_us: float = 5000.0,
    tier_pins=None,
    shard_devices: str | None = None,
) -> ServingShards:
    """Wire K (runner → dispatcher) lanes over a K-way split of `cfg`.

    All lanes share `metrics`, `hub` and `sink`. `shard_devices` is the
    ``--shard-devices`` placement spec (parse_shard_devices) committing
    each lane's books and jit executables to its device. With
    `with_dispatchers` False the caller drives dispatch itself
    (benches/tests)."""
    metrics = metrics or Metrics()
    router = ShardRouter(num_shards)
    placement = parse_shard_devices(shard_devices, num_shards)
    lanes: list[ServingLane] = []
    for i in range(num_shards):
        runner = make_lane_runner(
            cfg, router, i, metrics=metrics, hub=hub,
            pipeline_inflight=pipeline_inflight, native_lanes=native_lanes,
            device=placement[i],
            megadispatch_max_waves=megadispatch_max_waves,
            tier_pins=tier_pins)
        dispatcher = None
        if with_dispatchers:
            dispatcher = make_lane_dispatcher(
                runner, sink=sink, hub=hub, window_ms=window_ms,
                metrics=metrics, native=native, native_lanes=native_lanes,
                mega_max_waves=megadispatch_max_waves,
                mega_latency_us=megadispatch_latency_us)
        lanes.append(ServingLane(i, runner, dispatcher))
    return ServingShards(lanes, router, metrics=metrics, sink=sink,
                         sample_interval_s=sample_interval_s)
