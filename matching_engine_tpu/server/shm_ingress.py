"""The shared-memory ingress poller — the server half of the zero-copy
edge (ROADMAP Open item 3a; native/me_shmring.cpp is the ring itself).

One thread (`shm-poller`, a declared analyzer role) owns the segment:
it pops committed record runs from the request ring, screens them
through the SAME pipeline as the batch RPCs (structural record_flaws +
the vectorized admission screens, via service.run_oprec_records), routes
and dispatches them through the serving lanes, and answers positionally
through the response ring as fixed 48-byte MeShmResp records keyed by
ring sequence. Per-op work on the ingress side is one memcpy out of the
ring slot and the numpy screen passes — no proto, no python per-op.

The request ring is MULTI-PRODUCER (ring v2): every admitted record
carries the writer lane that committed it, the poller meters per-writer
flow (me_ingress_writer<i>_records / _rejects, f-string series — one per
lane that has actually published) and stamps the writer into each
response so the ring demuxes it onto that writer's private response
sub-ring. Crash-safety is the ring's contract (per-slot commit words,
claim-stamp attribution, pid-leased torn recovery — see the
me_shmring.cpp header); this module just surfaces the recoveries as
me_ingress_torn_recoveries and keeps serving.
"""

from __future__ import annotations

import threading

import numpy as np

from matching_engine_tpu.domain import oprec
from matching_engine_tpu.utils.obs import warn_rate_limited


class ShmIngress:
    """Owns the shm segment + the poller thread. Created by build_server
    when --shm-ingress PATH is set; closed before the dispatchers drain
    (an in-flight poll batch completes through the normal waiters)."""

    def __init__(self, path: str, service, metrics, slots: int = 4096,
                 resp_slots: int = 8192, poll_max: int = 2048,
                 torn_wait_ms: float = 50.0, window_ms: float = 2.0):
        from matching_engine_tpu import native as me_native

        self.service = service
        self.metrics = metrics
        self.poll_max = poll_max
        self.torn_wait_us = max(1, int(torn_wait_ms * 1e3))
        self.window_us = max(1, int(window_ms * 1e3))
        self.ring = me_native.ShmRing(path, create=True, slots=slots,
                                      resp_slots=resp_slots)
        # Register the literal zeros (PR 8 convention): a scrape shows
        # the me_ingress_* series from boot, not first traffic — the
        # soak's missing-metric check depends on it.
        for name in ("ingress_records", "ingress_batches",
                     "ingress_rejects", "ingress_torn_recoveries",
                     "ingress_batch_failures"):
            metrics.inc(name, 0)
        self._sample_gauges()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, name="shm-poller",
                                        daemon=True)

    def start(self) -> "ShmIngress":
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        self.ring.shutdown()  # unblocks the poll + attached clients
        self._thread.join(timeout=10)
        self.ring.close()     # unmap + unlink (owner side)

    # -- the poller thread --------------------------------------------------

    def _run(self) -> None:
        from matching_engine_tpu import native as me_native

        m = self.metrics
        while not self._stop.is_set():
            body, seqs, torn = self.ring.poll(
                self.poll_max, wait_us=100_000,
                torn_wait_us=self.torn_wait_us,
                window_us=self.window_us)
            if body is None:
                break  # segment shut down
            if torn:
                m.inc("ingress_torn_recoveries", torn)
            self._sample_gauges()
            n = len(seqs)
            if n == 0:
                continue
            m.inc("ingress_batches")
            m.inc("ingress_records", n)
            arr = np.frombuffer(body, dtype=oprec.OPREC_DTYPE)
            try:
                ok, oids, errs, rems, reasons, flaws = (
                    self.service.run_oprec_records(arr))
            except Exception as e:  # noqa: BLE001 — the poller must
                # survive any per-batch failure; answer the batch as
                # engine errors instead of stranding the client.
                m.inc("dispatch_errors")
                m.inc("ingress_batch_failures")
                warn_rate_limited(
                    "shm-ingress-batch",
                    f"[shm-ingress] batch failed: "
                    f"{type(e).__name__}: {e} "
                    f"(me_ingress_batch_failures_total carries the rate)")
                ok = [False] * n
                oids = [""] * n
                errs = ["engine error"] * n
                rems = [0] * n
                reasons = None
                flaws = [None] * n
            okv = np.fromiter(ok, dtype=bool, count=n)
            rejects = n - int(np.count_nonzero(okv))
            if rejects:
                m.inc("ingress_rejects", rejects)
            # Per-writer metering (multi-producer ring): the commit path
            # stamped each record's writer lane; count records/rejects
            # per lane that actually published this batch (f-string
            # series — the doc-lint dynamic-name rule, like the per-lane
            # queue gauges).
            for w, cnt in zip(*np.unique(arr["writer"],
                                         return_counts=True)):
                m.inc(f"ingress_writer{int(w)}_records", int(cnt))
            if rejects:
                for w, cnt in zip(*np.unique(arr["writer"][~okv],
                                             return_counts=True)):
                    m.inc(f"ingress_writer{int(w)}_rejects", int(cnt))
            # Positional responses, keyed by ring sequence, built as ONE
            # numpy SHM_RESP_DTYPE array (no per-op python on the common
            # all-accepted path). Reject reasons are codes (the shm edge
            # carries no free text): the admission pass's own code when
            # it screened the record, else classified off the shared
            # error vocabulary.
            resp = np.zeros(n, dtype=oprec.SHM_RESP_DTYPE)
            resp["seq"] = seqs
            resp["kind"] = np.maximum(
                arr["op"].astype(np.int16) - 1, 0).astype(np.uint8)
            # Echo the writer lane: the ring demuxes each response onto
            # this writer's private sub-ring (per-writer ack exactness).
            resp["writer"] = arr["writer"].astype(np.uint8)
            resp["ok"] = okv
            if okv.any():
                resp["remaining"][okv] = np.fromiter(
                    rems, dtype=np.int64, count=n)[okv]
            # Order ids ride every response that has one (accepted ops
            # AND rejected cancels/amends, which echo their target).
            oid_arr = np.array(oids, dtype="S24")
            resp["order_id"] = oid_arr
            resp["oid_len"] = np.char.str_len(oid_arr).astype(np.uint8)
            bad = np.nonzero(~okv)[0]
            if len(bad):
                codes = np.full(len(bad), oprec.REASON_REJECTED,
                                dtype=np.uint8)
                if reasons is not None:
                    scr = reasons[bad]
                    codes[scr != 0] = scr[scr != 0]
                else:
                    scr = np.zeros(len(bad), dtype=np.uint8)
                unscr = scr == 0
                if unscr.any():
                    flawed = np.fromiter(
                        (flaws[i] is not None for i in bad),
                        dtype=bool, count=len(bad))
                    errv = np.array([errs[i] for i in bad])
                    codes[unscr & flawed] = oprec.REASON_MALFORMED
                    codes[unscr & ~flawed
                          & (errv == "server overloaded")] = \
                        oprec.REASON_RING_FULL
                    codes[unscr & ~flawed & (errv == "engine error")] = \
                        oprec.REASON_ENGINE
                resp["reason"][bad] = codes
                resp["ok"][bad] = 0
                resp["remaining"][bad] = 0
            self.ring.respond_payload(resp.tobytes(), n)

    def _sample_gauges(self) -> None:
        s = self.ring.stats()
        m = self.metrics
        m.set_gauge("ingress_ring_depth", s["depth"])
        m.set_gauge("ingress_doorbell_wakes", s["doorbell_wakes"])
        m.set_gauge("ingress_resp_dropped", s["resp_dropped"])
        m.set_gauge("ingress_writers", self.ring.writer_count())
