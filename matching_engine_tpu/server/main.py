"""Server bootstrap: `python -m matching_engine_tpu.server.main --addr ...`.

Process shape mirrors the reference's main (src/server/main.cpp:17-70):
--addr flag (default 0.0.0.0:50051), db directory creation, insecure creds,
port-bind failure check, SIGINT/SIGTERM -> graceful shutdown with a 2s
deadline, typed exit codes (1 = storage init failure, 2 = bind failure,
3 = fatal). Extended with engine/dispatcher flags and crash recovery: on
boot, open orders (status NEW/PARTIALLY_FILLED) are replayed from SQLite
into the device books in created_ts order, and the OID sequence resumes
from MAX(order_id).
"""

from __future__ import annotations

import argparse
import contextlib
import os
import signal
import sys
import threading
from concurrent import futures as cf

import grpc

from matching_engine_tpu.engine.book import EngineConfig
from matching_engine_tpu.engine.kernel import OP_REST
from matching_engine_tpu.proto.rpc import add_matching_engine_servicer
from matching_engine_tpu.server.dispatcher import BatchDispatcher, NativeRingDispatcher
from matching_engine_tpu.server.engine_runner import EngineOp, EngineRunner, OrderInfo
from matching_engine_tpu.server.service import MatchingEngineService
from matching_engine_tpu.server.streams import StreamHub
from matching_engine_tpu.storage import AsyncStorageSink, Storage
from matching_engine_tpu.utils.checkpoint import (
    CheckpointDaemon,
    latest_checkpoint,
    restore_runner,
)
from matching_engine_tpu.utils.metrics import Metrics
from matching_engine_tpu.utils.obs import FlightRecorder, ObsServer, TraceExporter
from matching_engine_tpu.utils.tracing import set_host_tracer, trace


def recover_books(runner: EngineRunner, storage: Storage) -> int:
    """Rebuild device books from the durable store after a restart.

    The reference sketches this (best_bid/best_ask over status IN (0,1)) but
    never performs it (SURVEY.md §5.4). Replays open LIMIT orders, oldest
    first, with their *remaining* quantity, as OP_REST dispatches — open
    orders by definition RESTED, so re-resting reproduces the book exactly
    in both trading modes (a continuous book never stands crossed, and a
    call-period book persisted crossed MUST NOT match itself on replay).
    No persistence or stream side effects.
    """
    runner.seed_oid_sequence(storage.load_next_oid_seq())
    rows = storage.open_orders()
    ops = []
    skipped_foreign = 0
    for (order_id, client_id, symbol, side, otype, price, qty, remaining, status) in rows:
        if not runner.owns_symbol(symbol):
            # Cluster resize moved this symbol's home: do NOT rebook it
            # here (two hosts would diverge on one name). Its rows stay in
            # this host's durable store for an operator-driven migration.
            skipped_foreign += 1
            continue
        if runner.slot_acquire(symbol) is None:
            print(f"[SERVER] recovery: symbol axis full, dropping {order_id}")
            continue
        num = int(order_id.split("-", 1)[1]) if order_id.startswith("OID-") else 0
        info = OrderInfo(
            oid=num, order_id=order_id, client_id=client_id, symbol=symbol,
            side=side, otype=otype, price_q4=price, quantity=qty,
            remaining=remaining, status=status, handle=runner.assign_handle(),
        )
        runner.orders_by_handle[info.handle] = info
        runner.orders_by_id[order_id] = info
        ops.append(EngineOp(OP_REST, info))
    if skipped_foreign:
        print(f"[SERVER] recovery: {skipped_foreign} open orders belong to "
              f"symbols homed on other hosts; left in SQLite for migration")
    if ops:
        runner.run_dispatch(ops)
    return len(ops)


def _boot_runner(make, storage, owner_rows, ckpt_root, log, tag=""):
    """Construct + recover one runner: STP owner-registry preload,
    checkpoint fast-path restore with full-replay fallback, SQLite book
    recovery. Shared by the single-lane boot and each partitioned
    serving lane (which passes its own checkpoint subdir and whose
    owns_symbol filter confines the replay to its shard)."""
    runner = make()
    runner.load_owner_ids(owner_rows)
    ckpt = latest_checkpoint(ckpt_root) if ckpt_root else None
    if ckpt is not None:
        try:
            replayed = restore_runner(runner, ckpt, storage)
            # Shard-cut identity guard: a reboot that changes --symbols
            # and --serve-shards PROPORTIONALLY passes restore_runner's
            # semantic-key and slice checks (both compare per-lane
            # shapes), yet the snapshot belongs to a DIFFERENT cut of
            # the symbol space — restoring it would put live books for
            # symbols this lane no longer owns next to the owning
            # lane's replayed ones. Foreign symbols => full replay.
            foreign = [s for s in runner.symbols
                       if not runner.owns_symbol(s)]
            if foreign:
                raise ValueError(
                    f"checkpoint covers {len(foreign)} symbol(s) outside "
                    f"this lane's shard cut (e.g. {foreign[0]}) — shard "
                    f"count/symbol axis changed")
            if log:
                print(f"[SERVER] restored{tag} {ckpt} "
                      f"(+{replayed} reconcile ops)")
        except Exception as e:  # corrupt/skewed checkpoint -> full replay
            print(f"[SERVER] checkpoint restore{tag} failed "
                  f"({type(e).__name__}: {e}); full replay")
            runner = make()
            runner.load_owner_ids(owner_rows)
            ckpt = None
    if ckpt is None:
        recovered = recover_books(runner, storage)
        if recovered and log:
            print(f"[SERVER] recovered{tag} {recovered} open orders "
                  f"into device books")
    return runner


def config_error(combo: str, detail: str, supported: str) -> None:
    """Structured boot refusal: ONE parseable stderr line naming the
    refused flag combination, why, and the supported alternatives —
    mirroring the compatibility matrix in docs/OPERATIONS.md so an
    operator (or a boot-wrapping script grepping CONFIG-ERROR) gets the
    fix, not just the failure."""
    print(f"[SERVER] CONFIG-ERROR combo=[{combo}]: {detail}; "
          f"supported: {supported}", file=sys.stderr)


def build_server(
    addr: str,
    db_path: str,
    cfg: EngineConfig,
    window_ms: float = 2.0,
    rpc_workers: int = 256,
    log: bool = True,
    checkpoint_dir: str | None = None,
    checkpoint_interval_s: float = 30.0,
    native: bool = True,
    mesh=None,
    gateway_addr: str | None = None,
    pipeline_inflight: int = 2,
    native_lanes: bool = False,
    flight_dir: str | None = None,
    feed_depth: int = 1 << 16,
    feed_spill_dir: str | None = None,
    stream_maxsize: int = 1024,
    serve_shards: int = 1,
    megadispatch_max_waves: int = 1,
    megadispatch_latency_us: float = 5000.0,
    busy_poll_us: float = 0.0,
    book_cache_ms: float = 0.0,
    proto_reuse: bool = False,
    trace_dir: str | None = None,
    trace_sample_every: int = 64,
    audit: bool = False,
    audit_sample: int = 8,
    oplog_ship: bool = False,
    standby_addr: str | None = None,
    standby_auto_promote_s: float = 0.0,
    standby_attest: bool = True,
    tier_pins: dict | None = None,
    admission_cfg=None,          # admission.AdmissionConfig | None
    shm_ingress_path: str | None = None,
    shm_slots: int = 4096,
    shm_resp_slots: int = 8192,
    shm_torn_ms: float = 50.0,
    shard_devices: str | None = None,
    feed_fanin: str = "hub",
):
    """Wire the full stack; returns (grpc server, bound port, parts dict).

    With native=True (the default) and the C++ runtime built, the op ring /
    batching window and the SQLite writer run in native code
    (native/me_native.cpp); otherwise the pure-Python twins serve. Reads
    (recovery, book queries, OID reseed) always go through Storage.

    With native_lanes=True the serving hot path additionally runs through
    the C++ lane engine (native/me_lanes.cpp via server/native_lanes.py):
    lane build, host checks, completion/storage decode all happen native,
    Python works per dispatch. Single-device only; requires the built
    native runtime.

    With serve_shards=K (> 1) the serving stack partitions into K
    independent symbol-sharded lanes (server/shards.py): a router at the
    edge, one (ring → dispatcher thread → runner) column per shard, each
    pinned to its own device when several are visible. Incompatible with
    --mesh (the ShardedEngine path keeps the market-wide formulation).

    With megadispatch_max_waves=M (> 1) the Python dispatch path
    coalesces deep-queue backlogs into stacked device scans (one XLA
    dispatch per M waves, compacted readback — engine_runner._prepare_mega
    + the dispatcher's adaptive controller). M=1 (the default) keeps
    today's serial schedule exactly; output is bit-identical either way.
    Single-device python-route only: --native-lanes builds its lanes
    wave-by-wave in C++, and --mesh decodes from shards, so both ignore
    it (logged at boot).
    """
    from matching_engine_tpu import native as _me_native

    if serve_shards > 1 and mesh is not None:
        raise SystemExit(3)  # partitioned lanes vs mesh: pick one
    if megadispatch_max_waves > 1 and mesh is not None:
        # The mesh decodes from addressable shards — it never routes
        # through the stacked scan. (The native lane engine DOES: it
        # builds [M, S, B, 7] stacks and decodes compacted mega
        # completions in C++ — me_lanes.cpp wave_mega/decode_mega.)
        print("[SERVER] --megadispatch-max-waves applies to single-device "
              "serving only; ignoring it under --mesh")
        megadispatch_max_waves = 1

    if native_lanes:
        if mesh is not None:
            raise SystemExit(3)  # lane engine is single-device (see runner)
        if not (native and _me_native.available()):
            print("[SERVER] --native-lanes needs the built native runtime "
                  "(libme_native.so); run scripts/build_native.sh",
                  file=sys.stderr)
            raise SystemExit(2)

    storage = Storage(db_path)
    if not storage.init():
        raise SystemExit(1)
    if standby_addr is not None:
        existing = storage.count("orders")
        if existing:
            # The runbook's "fresh --db" rule, enforced (and enforced
            # HERE, before any engine threads start): boot recovery
            # would restore this store's orders into the books, and the
            # standby's from-start op-log replay would then apply the
            # same history ON TOP of them — double-applied fills and a
            # guaranteed attestation divergence (or, unattested, wrong
            # read-only answers served with /replz green).
            print(f"[SERVER] --standby requires a fresh --db: this "
                  f"store already holds {existing} order(s); the "
                  f"from-start op-log replay would re-apply the same "
                  f"history on top of the recovered books. Re-bootstrap "
                  f"with a new --db file.", file=sys.stderr)
            raise SystemExit(3)

    metrics = Metrics()
    # Flight recorder: always recording (cheap, per dispatch); dumps only
    # when a dump dir is configured (SIGUSR2 / fatal dispatch error /
    # clean shutdown). Rides on the registry so every pipeline layer that
    # holds `metrics` can record without constructor churn.
    recorder = FlightRecorder(dump_dir=flight_dir)
    metrics.recorder = recorder
    # Back-reference so a dump can capture the megadispatch-controller /
    # lane-balance gauges the tail spike happened under.
    recorder.metrics = metrics
    # Trace exporter (--trace-dir): sampled per-dispatch Chrome traces.
    # Rides the registry like the recorder; host spans (tracing.span,
    # sink commits) fold into the same file via the module-global hook.
    tracer = None
    if trace_dir:
        tracer = TraceExporter(trace_dir, metrics=metrics,
                               sample_every=trace_sample_every)
        metrics.tracer = tracer
        set_host_tracer(tracer)
    # Sequenced feed (feed/): every stream event gets a per-(channel, key)
    # monotonic seq at publish and lands in the retransmission store, so
    # reconnecting/slow clients recover via resume_from_seq instead of
    # silent drop-oldest loss. feed_depth 0 restores the legacy
    # unsequenced feed (and lets the decode path skip event materialization
    # when nobody subscribes — the max-throughput bench configuration).
    sequencer = None
    if feed_depth:
        from matching_engine_tpu.feed import FeedSequencer

        sequencer = FeedSequencer(metrics=metrics, depth=feed_depth,
                                  spill_dir=feed_spill_dir)
    hub = StreamHub(maxsize=stream_maxsize, metrics=metrics,
                    sequencer=sequencer)
    # Epoch-consistent feed fan-in (--feed-fanin merged, feed/fanin.py):
    # each lane publishes through its own sequencer domain (per-lane seq
    # + venue epoch) into one merger thread, so K lanes stop serializing
    # their publish tails through the hub lock. "hub" (default) keeps
    # the single locked hub — the K=1/compat path, bit-parity pinned.
    fanin = None
    if feed_fanin not in ("hub", "merged"):
        print(f"[SERVER] --feed-fanin {feed_fanin!r}: expected hub|merged",
              file=sys.stderr)
        raise SystemExit(3)
    if feed_fanin == "merged":
        # Enforced HERE, not only in main()'s argv parsing (programmatic
        # callers take the same seam):
        if serve_shards <= 1:
            config_error(
                "--feed-fanin merged without --serve-shards K>1",
                "the merge exists to decouple K lanes' publish tails",
                "--feed-fanin merged with --serve-shards K>1; "
                "--feed-fanin hub at any K")
            raise SystemExit(3)
        if gateway_addr is not None or standby_addr is not None:
            config_error(
                "--feed-fanin merged with --gateway-addr/--standby",
                "the gateway bridge and the standby applier publish "
                "through the hub directly — bypassing the merge would "
                "interleave stamped and unstamped lanes",
                "--feed-fanin merged with the grpcio/shm edges on a "
                "primary; --feed-fanin hub otherwise")
            raise SystemExit(3)
        from matching_engine_tpu.feed.fanin import FeedFanIn

        fanin = FeedFanIn(hub, serve_shards, metrics=metrics)
        if log:
            print(f"[SERVER] feed fan-in: sequenced merge over "
                  f"{serve_shards} lane domains")
    # Online surveillance (--audit, matching_engine_tpu/audit/): a
    # per-lane DropCopyPublisher republishes every dispatch's storage
    # rows as sequenced lifecycle records at the decode boundary, and ONE
    # shared InvariantAuditor consumes them in-process — proving
    # continuously that book, store, and feed agree. With the feed
    # disabled the records still publish/audit, just unsequenced (the
    # seq-continuity invariant is then vacuous and replay unavailable).
    auditor = None
    audit_pump = None
    if audit:
        from matching_engine_tpu.audit import AuditPump, InvariantAuditor

        # The pump is one more pure-python thread alternating with the
        # drain loops' GIL-released native/device calls; at the default
        # 5ms switch interval a drain thread returning from C convoys
        # behind the pump's whole quantum (the --serve-shards lesson).
        sys.setswitchinterval(min(sys.getswitchinterval(), 500 / 1e6))

        if sequencer is None:
            print("[SERVER] WARNING: --audit without the sequenced feed "
                  "(--feed-depth 0): drop-copy records are unsequenced — "
                  "loss between decode and publish is undetectable and "
                  "resume/replay is unavailable")
        auditor = InvariantAuditor(metrics, sample=audit_sample,
                                   db_path=db_path)
        # One out-of-band worker for all lanes: enqueue order (each
        # lane's decode order, interleaved) is the audit stamp order.
        audit_pump = AuditPump(metrics)

    def make_dropcopy(r, lane_hub=None):
        if auditor is None:
            return None
        from matching_engine_tpu.audit import DropCopyPublisher

        # With merged fan-in the lane's drop-copy rows ride its sequencer
        # domain too (the audit stamp-order invariant holds because ONE
        # merger delivers into the hub lock in merge order).
        r.dropcopy = DropCopyPublisher(
            lane_hub if lane_hub is not None else hub, metrics,
            auditor=auditor, runner=r, pump=audit_pump)
        return r.dropcopy

    # Warm-standby replication, primary side (--oplog-ship,
    # replication/oplog.py): every admitted dispatch's ops republish as
    # ONE sequenced oplog event; a standby applies them deterministically.
    # Needs the sequenced feed (the retransmission window IS the standby's
    # catch-up budget) and the EngineOp dispatch route.
    oplog_shipper = None
    if oplog_ship:
        if native_lanes or gateway_addr is not None or mesh is not None:
            # Enforced HERE, not only in main()'s argv parsing: the
            # shipper re-encodes EngineOps at the drain loops, and the
            # C++ lane/gateway drains and the mesh path never build
            # them — a programmatic caller combining these would get a
            # heartbeat-only shipper whose standby reads lag 0 while
            # mirroring NOTHING.
            print("[SERVER] oplog_ship runs on the EngineOp dispatch "
                  "routes only: drop native_lanes/gateway_addr/mesh",
                  file=sys.stderr)
            raise SystemExit(3)
        if sequencer is None:
            print("[SERVER] --oplog-ship needs the sequenced feed "
                  "(--feed-depth > 0)", file=sys.stderr)
            raise SystemExit(3)
        from matching_engine_tpu.replication import OpLogShipper

        oplog_shipper = OpLogShipper(hub, metrics)

    if cfg.tiers and (native_lanes or mesh is not None):
        # Enforced HERE, not only in main()'s argv parsing: the C++ lane
        # engine builds whole-grid waves for ONE capacity and the mesh
        # shards one uniform book — a programmatic caller combining them
        # with a tier spec would step books that don't exist.
        print("[SERVER] --book-tiers runs on the single-process python "
              "dispatch routes (composes with --serve-shards): drop "
              "native_lanes/mesh", file=sys.stderr)
        raise SystemExit(3)

    def make_runner():
        if native_lanes:
            from matching_engine_tpu.server.native_lanes import (
                NativeLanesRunner,
            )

            return NativeLanesRunner(
                cfg, metrics, hub=hub,
                pipeline_inflight=pipeline_inflight,
                megadispatch_max_waves=megadispatch_max_waves)
        if cfg.tiers:
            from matching_engine_tpu.server.tiered_runner import (
                TieredEngineRunner,
            )

            return TieredEngineRunner(
                cfg, metrics, hub=hub,
                pipeline_inflight=pipeline_inflight,
                megadispatch_max_waves=megadispatch_max_waves,
                tier_pins=tier_pins)
        return EngineRunner(cfg, metrics, mesh=mesh, hub=hub,
                            pipeline_inflight=pipeline_inflight,
                            megadispatch_max_waves=megadispatch_max_waves)

    # STP identity registry loads BEFORE any restore/recovery replay — the
    # replay derives owner lanes via _owner_for, and a hash-colliding
    # client must resolve to its persisted id, not first-arrival order.
    owner_rows = storage.load_owner_ids()
    if owner_rows is None:
        print("[SERVER] WARNING: owner_ids registry unreadable — STP "
              "identities re-derive from hashes; collision remaps may "
              "differ from previously persisted assignments")
        owner_rows = []
    router = None
    lanes = None
    if serve_shards > 1:
        # K lanes alternate short GIL-held python sections with
        # GIL-released native/device calls; at CPython's default 5ms
        # switch interval a drain thread returning from C waits out the
        # GIL holder's whole quantum (the convoy effect) and lane
        # scaling goes negative. 500us restores the handoff granularity
        # this architecture needs (measured in BENCH_METHOD.md).
        sys.setswitchinterval(500 / 1e6)
        # Partitioned serving boot: K lane runners, each restored from its
        # own checkpoint subdir (or by replaying only its shard's rows —
        # owns_symbol routes by the shard cut). The durable store itself
        # is shard-agnostic, so a db written at any K boots at any other.
        from matching_engine_tpu.server.shards import (
            ServingLane,
            ShardRouter,
            make_lane_runner,
            parse_shard_devices,
        )

        router = ShardRouter(serve_shards)
        try:
            # Device-aware placement: each lane's books and jit
            # executables commit to its device (EngineRunner device_put's
            # at construction; jit dispatches follow the operands).
            placement = parse_shard_devices(shard_devices, serve_shards)
        except ValueError as e:
            print(f"[SERVER] bad --shard-devices: {e}", file=sys.stderr)
            raise SystemExit(3)
        # ONE publisher per lane: a lane's seq domain must be a single
        # monotonic line across its runner, dispatcher and drop-copy.
        lane_hubs = [fanin.lane_publisher(i) if fanin is not None else hub
                     for i in range(serve_shards)]
        if log and any(d is not None for d in placement):
            placed = ", ".join(
                f"lane{i}->dev{getattr(d, 'id', '?')}" if d is not None
                else f"lane{i}->default"
                for i, d in enumerate(placement))
            print(f"[SERVER] shard placement "
                  f"({shard_devices or 'auto'}): {placed}")
        lanes = []
        for i in range(serve_shards):
            lanes.append(ServingLane(i, _boot_runner(
                lambda _i=i: make_lane_runner(
                    cfg, router, _i, metrics=metrics, hub=lane_hubs[_i],
                    pipeline_inflight=pipeline_inflight,
                    native_lanes=native_lanes,
                    device=placement[_i],
                    megadispatch_max_waves=megadispatch_max_waves,
                    tier_pins=tier_pins),
                storage, owner_rows,
                os.path.join(checkpoint_dir, f"shard-{i}")
                if checkpoint_dir else None,
                log, tag=f" lane {i}")))
        runners = [lane.runner for lane in lanes]
        runner = runners[0]
    else:
        # Fast path: restore the newest device-book snapshot and replay
        # only the post-snapshot delta from SQLite; else full replay.
        runner = _boot_runner(make_runner, storage, owner_rows,
                              checkpoint_dir, log)
        runners = [runner]
    if auditor is not None:
        # Orders recovered/replayed at boot predate the drop-copy stream:
        # ids below the floor are exempt from shadow tracking (a fill
        # against one is pre-boot state, not corruption). Per residue
        # class — strided lanes recover unequal counts, and one global
        # max would exempt the other lanes' genuinely new ids.
        auditor.set_oid_floors(
            [(r.next_oid_num, r.oid_offset, r.oid_stride)
             for r in runners])
    # Restore a persisted call period (each host records its own flag in
    # its durable store — crossedness alone can't prove the ABSENCE of a
    # call period, e.g. non-crossing rests only).
    from matching_engine_tpu.engine.book import auction_capacity_max

    auction_ok = cfg.capacity <= auction_capacity_max(cfg.kernel)
    if storage.get_meta("auction_mode") == "1":
        if auction_ok:
            for r in runners:  # a call period is venue-wide: every lane
                r.auction_mode = True
            if log:
                print("[SERVER] durable store records an OPEN auction call "
                      "period: resuming it")
        else:
            print("[SERVER] WARNING: durable store records an open call "
                  "period, but this capacity cannot run auctions — "
                  "resuming CONTINUOUS trading instead")
    # Safety net: a crossed book after recovery can only come from state
    # persisted during a call period (continuous matching never leaves
    # one standing) — resume rather than expose those books to the
    # continuous maker scan.
    crossed = [s for r in runners for s in r.crossed_symbols()]
    if crossed and not runner.auction_mode and auction_ok:
        for r in runners:
            r.auction_mode = True
        print(f"[SERVER] {len(crossed)} recovered book(s) stand crossed "
              f"(e.g. {crossed[0]}): resuming the auction call period")
    elif crossed and not runner.auction_mode:
        # Unreachable for every admissible EngineConfig (auction_ok holds
        # at all supported capacities since the wide-sum uncross), kept
        # as a REFUSAL: serving continuous trading over standing
        # maker-maker crosses breaks the invariant every STP/recovery
        # argument rests on (ADVICE r4 low) — the operator must restart
        # at an auction-capable capacity to uncross.
        print(f"[SERVER] FATAL: {len(crossed)} recovered book(s) stand "
              f"crossed (e.g. {crossed[0]}) and this capacity cannot run "
              f"auctions; refusing to serve a crossed book under "
              f"continuous matching. Restart at an auction-capable "
              f"capacity to uncross.")
        raise SystemExit(1)  # same typed exit as an unusable store
    if runner.auction_mode:
        print("[SERVER] auction call period OPEN — an ALL-symbols "
              "RunAuction (empty symbol) reopens continuous trading")
    # Wire persistence AFTER restore (the restore read, not wrote) and
    # record the current state so a pre-meta database gains the row.
    # One meta row serves every lane: the persisted flag is the OR across
    # lanes, so it stays "1" until the LAST lane's call period closes
    # (any lane with standing rests must resume accumulating on reboot).
    persist_mode = (lambda v: storage.set_meta(
        "auction_mode", "1" if any(r.auction_mode for r in runners) else "0"))
    for r in runners:
        r.persist_auction_mode = persist_mode
        r.persist_owner_ids = storage.insert_owner_ids
        r.flush_owner_ids()  # assignments derived during recovery replay
    runner.persist_auction_mode(runner.auction_mode)

    from matching_engine_tpu import native as me_native

    use_native = native and me_native.available()
    if use_native:
        # C++ writer: stage_sink_commit_us is a python-sink figure only
        # (and the auditor's store probes run on their dispatch-count
        # cadence — no commit hook to ride).
        sink = me_native.NativeStorageSink(db_path)
    else:
        sink = AsyncStorageSink(
            storage, metrics=metrics,
            # --audit: store<->feed probes ride each commit, on the sink
            # thread, where the rows just became readable.
            on_commit=auditor.notify_commit if auditor is not None
            else None)
    # Order-preserving overflow buffer: a full sink queue defers batches
    # instead of dropping them; the checkpoint flush barrier drains it.
    from matching_engine_tpu.storage.async_sink import SpillingSink

    sink = SpillingSink(sink, metrics)
    checkpointer = None
    checkpointers = []
    shards = None
    if serve_shards > 1:
        from matching_engine_tpu.server.shards import (
            ServingShards,
            make_lane_dispatcher,
        )

        for lane in lanes:
            if checkpoint_dir:
                lane.checkpointer = CheckpointDaemon(
                    lane.runner, sink,
                    os.path.join(checkpoint_dir, f"shard-{lane.shard_id}"),
                    interval_s=checkpoint_interval_s, storage=storage,
                ).start()
                checkpointers.append(lane.checkpointer)
            if native_lanes:
                # Boot-time Python-path mutations are done for this lane:
                # flip directory authority to its C++ engine before any
                # serving loop can dispatch.
                lane.runner.adopt_from_python()
            lane.dispatcher = make_lane_dispatcher(
                lane.runner, sink=sink, hub=lane_hubs[lane.shard_id],
                window_ms=window_ms,
                metrics=metrics, native=use_native,
                native_lanes=native_lanes,
                mega_max_waves=megadispatch_max_waves,
                mega_latency_us=megadispatch_latency_us,
                busy_poll_us=busy_poll_us,
                dropcopy=make_dropcopy(lane.runner,
                                       lane_hubs[lane.shard_id]),
                oplog=oplog_shipper, lane_id=lane.shard_id)
        shards = ServingShards(lanes, router, metrics=metrics, sink=sink)
        dispatcher = lanes[0].dispatcher
    else:
        if checkpoint_dir:
            checkpointer = CheckpointDaemon(
                runner, sink, checkpoint_dir,
                interval_s=checkpoint_interval_s, storage=storage,
            ).start()
            checkpointers.append(checkpointer)
        if native_lanes:
            # All boot-time Python-path mutations (recovery replay,
            # restore, auction-mode resume) are done: flip directory
            # authority to the C++ lane engine before any serving loop
            # can dispatch.
            runner.adopt_from_python()
            from matching_engine_tpu.server.dispatcher import (
                LaneRingDispatcher,
            )

            dispatcher = LaneRingDispatcher(
                runner, sink=sink, hub=hub, window_ms=window_ms,
                busy_poll_us=busy_poll_us,
                mega_max_waves=megadispatch_max_waves,
                dropcopy=make_dropcopy(runner),
            )
        elif use_native:
            dispatcher = NativeRingDispatcher(
                runner, sink=sink, hub=hub, window_ms=window_ms,
                mega_max_waves=megadispatch_max_waves,
                mega_latency_us=megadispatch_latency_us,
                busy_poll_us=busy_poll_us,
                dropcopy=make_dropcopy(runner),
                oplog=oplog_shipper,
            )
        else:
            dispatcher = BatchDispatcher(
                runner, sink=sink, hub=hub, window_ms=window_ms,
                mega_max_waves=megadispatch_max_waves,
                mega_latency_us=megadispatch_latency_us,
                busy_poll_us=busy_poll_us,
                dropcopy=make_dropcopy(runner),
                oplog=oplog_shipper)
    if log:
        layer = ("native lanes (C++ build+decode)" if native_lanes
                 else "native (C++)" if use_native else "python")
        if serve_shards > 1:
            layer += f" x {serve_shards} partitioned lanes"
        print(f"[SERVER] runtime layer: {layer}")
    # Vectorized per-client admission screens (server/admission.py): one
    # shared instance screens every ingress path — bulk edges as numpy
    # passes, per-op RPCs as 1-record batches.
    admission = None
    if admission_cfg is not None and admission_cfg.any_enabled:
        from matching_engine_tpu.server.admission import AdmissionScreens

        admission = AdmissionScreens(admission_cfg, metrics=metrics)
        if log:
            print(f"[SERVER] admission screens: {admission_cfg}")
    service = MatchingEngineService(runner, dispatcher, hub, metrics,
                                    log=log, shards=shards,
                                    book_cache_ms=book_cache_ms,
                                    proto_reuse=proto_reuse,
                                    admission=admission)
    # RunAuction rejects on an op-log-shipping primary (the uncross
    # bypasses the drain loops the shipper rides — a standby would
    # silently diverge); main() additionally refuses --auction-open.
    service.oplog_ship = oplog_shipper is not None

    # Warm-standby replica (--standby, replication/standby.py): mutation
    # RPCs stay closed (read_only) while the replica applies the
    # primary's op log through this very stack; `Promote` (or heartbeat
    # lapse with --standby-auto-promote-s) opens them.
    replica = None
    if standby_addr is not None:
        if sequencer is None:
            print("[SERVER] --standby needs the sequenced feed "
                  "(--feed-depth > 0)", file=sys.stderr)
            raise SystemExit(3)
        from matching_engine_tpu.replication import StandbyReplica

        service.read_only = True
        replica = StandbyReplica(
            standby_addr, runners=runners, shards=shards, sink=sink,
            hub=hub, sequencer=sequencer, storage=storage, metrics=metrics,
            service=service, auto_promote_s=standby_auto_promote_s,
            attest=standby_attest)
        service.replica = replica
        if log:
            print(f"[SERVER] STANDBY replica of {standby_addr} "
                  f"(read-only until Promote"
                  + (f"; auto-promote after "
                     f"{standby_auto_promote_s:.2f}s heartbeat lapse)"
                     if standby_auto_promote_s > 0 else ")"))

    # Receive limit sized to the batch edge's record cap (service
    # _BATCH_RECORD_CAP x 384-byte records ~ 25 MB) — the default 4 MB
    # would bounce a documented-size SubmitOrderBatch at the transport,
    # before the handler's own cap could answer it application-level.
    server = grpc.server(
        cf.ThreadPoolExecutor(max_workers=rpc_workers),
        options=[("grpc.max_receive_message_length", 32 << 20),
                 ("grpc.max_send_message_length", 32 << 20)])
    add_matching_engine_servicer(service, server)
    port = server.add_insecure_port(addr)
    if port == 0:
        print(f"[SERVER] failed to bind {addr}", file=sys.stderr)
        raise SystemExit(2)

    # The C++ serving edge (native/me_gateway.cpp): same wire contract on a
    # second port, hot path parsed/validated/answered in C++ around a dense
    # batch dispatch. Shares runner/sink/hub/service with the grpcio edge —
    # the dispatch lock serializes the two drain loops.
    bridge = None
    gateway_port = None
    if gateway_addr is not None:
        if not me_native.gateway_available():
            print("[SERVER] native gateway requested but library unavailable",
                  file=sys.stderr)
            raise SystemExit(2)
        from matching_engine_tpu.server.gateway_bridge import GatewayBridge

        gateway = me_native.NativeGateway(gateway_addr)
        bridge = GatewayBridge(
            gateway, runner, service, sink=sink, hub=hub, window_ms=window_ms,
            # Venue-wide pop cap: with shards, runner is ONE lane whose
            # cfg is the K-way split — sizing the batch from it would
            # shrink every gateway pop by K.
            max_batch=cfg.num_symbols * cfg.batch,
            native_lanes=native_lanes, shards=shards,
        )
        gateway_port = bridge.start()
        if log:
            print(f"[SERVER] native gateway on port {gateway_port}")

    # Zero-copy shared-memory ingress (--shm-ingress PATH,
    # server/shm_ingress.py): a co-located client writes oprec records
    # straight into a mapped ring; the poller thread screens and
    # dispatches them through the same pipeline as the batch RPCs.
    shm_ingress = None
    if shm_ingress_path is not None:
        if standby_addr is not None:
            # A standby's mutation surface is closed; an shm segment
            # would answer every record with the read-only reject while
            # looking like a live ingress edge. Refuse at boot.
            print("[SERVER] --shm-ingress is a mutation edge: not "
                  "available on a --standby replica", file=sys.stderr)
            raise SystemExit(3)
        if not (native and _me_native.available()):
            print("[SERVER] --shm-ingress needs the built native runtime "
                  "(libme_native.so); run scripts/build_native.sh",
                  file=sys.stderr)
            raise SystemExit(2)
        from matching_engine_tpu.server.shm_ingress import ShmIngress

        shm_ingress = ShmIngress(
            shm_ingress_path, service, metrics, slots=shm_slots,
            resp_slots=shm_resp_slots, torn_wait_ms=shm_torn_ms,
            window_ms=window_ms).start()
        if log:
            print(f"[SERVER] shm ingress ring at {shm_ingress_path} "
                  f"({shm_slots} slots, {shm_resp_slots} response slots)")

    parts = {
        "storage": storage, "sink": sink, "hub": hub,
        "dispatcher": dispatcher, "runner": runner, "service": service,
        "metrics": metrics, "checkpointer": checkpointer,
        "checkpointers": checkpointers, "shards": shards,
        "bridge": bridge, "gateway_port": gateway_port,
        "recorder": recorder, "sequencer": sequencer, "tracer": tracer,
        "auditor": auditor, "audit_pump": audit_pump,
        "oplog": oplog_shipper, "replica": replica, "runners": runners,
        "shm_ingress": shm_ingress, "admission": admission,
        "fanin": fanin,
    }
    return server, port, parts


def shutdown(server, parts, grace_s: float = 2.0) -> None:
    """Graceful drain: stop RPCs (2s deadline, as the reference's stopper
    thread does), close the dispatcher, flush the storage sink."""
    server.stop(grace_s).wait()
    if parts.get("shm_ingress") is not None:
        # BEFORE the dispatcher drain: the poller's in-flight batch
        # resolves through the normal waiters, then the segment unlinks.
        parts["shm_ingress"].close()
    if parts.get("replica") is not None:
        # BEFORE the hub/dispatcher teardown: the applier may be mid-
        # dispatch against the runner these drain.
        parts["replica"].close()
    if parts.get("oplog") is not None:
        parts["oplog"].close()  # heartbeat thread off the hub first
    if parts.get("bridge") is not None:
        parts["bridge"].close()
    parts["hub"].close_all()
    if parts.get("shards") is not None:
        parts["shards"].close()  # every lane's dispatcher + the sampler
    else:
        parts["dispatcher"].close()
    if parts.get("fanin") is not None:
        # AFTER the lane dispatchers (no new publishes), BEFORE the
        # sequencer flush: the merger drains every queued lane publish
        # into the hub — stamping/retaining them — then exits.
        parts["fanin"].close()
    if parts.get("sequencer") is not None:
        # Drain the spill flusher (completes any in-flight gap-fill
        # window and leaves a forensic record of the tail). The store —
        # memory AND spill — is per boot: the next boot starts a fresh
        # epoch dir and purges this one; clients resuming across the
        # restart observe an epoch rebase, not a replay.
        parts["sequencer"].flush_spill()
    for ckpt in (parts.get("checkpointers")
                 or ([parts["checkpointer"]] if parts.get("checkpointer")
                     else [])):
        try:
            ckpt.checkpoint_now()
        except Exception as e:  # a failed final snapshot must not block drain
            print(f"[SERVER] final checkpoint failed: {type(e).__name__}: {e}")
        ckpt.close()
    parts["sink"].close()
    if parts.get("audit_pump") is not None:
        # Drain the out-of-band surveillance queue BEFORE the final
        # store check: every dispatch's records must be audited.
        parts["audit_pump"].close()
    if parts.get("auditor") is not None:
        # The sink is flushed and closed: every probe the auditor still
        # holds must resolve strictly NOW — an order that never reached
        # the store is a finding, not lag.
        parts["auditor"].final_store_check()
        parts["auditor"].close()
    parts["storage"].close()
    if parts.get("tracer") is not None:
        # After the sink: its commit spans land before the finalize.
        set_host_tracer(None)
        parts["tracer"].close()
    if parts.get("recorder") is not None:
        # Last: the dump captures the fully-drained pipeline's tail.
        parts["recorder"].dump("shutdown")


def resolve_mesh(n: int, num_symbols: int):
    """Resolve --mesh N into a device mesh (None when N == 0).

    N counts TOTAL devices across all processes. Multi-process runs must
    use exactly the global mesh (every process has to build the same SPMD
    program over the same devices); single-process runs may take a leading
    slice of the local devices. Raises ValueError with a clean message on
    any misconfiguration — main() turns that into exit code 3.
    """
    if not n:
        return None
    if num_symbols % n != 0:
        raise ValueError(f"--symbols {num_symbols} not divisible by --mesh {n}")

    import jax

    from matching_engine_tpu.parallel.multihost import initialize, make_multihost_mesh

    initialize()  # no-op single-process; bootstraps DCN when configured
    mesh = make_multihost_mesh()
    if mesh.devices.size == n:
        return mesh
    if jax.process_count() > 1:
        raise ValueError(
            f"--mesh {n} != the {mesh.devices.size} devices of this "
            f"{jax.process_count()}-process cluster (N counts ALL devices)"
        )
    from matching_engine_tpu.parallel.sharding import make_mesh

    return make_mesh(n)  # raises ValueError if > visible devices


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="TPU-native matching engine server")
    p.add_argument("--addr", default="0.0.0.0:50051")
    p.add_argument("--db", default="db/matching_engine.db")
    p.add_argument("--symbols", type=int, default=1024, help="symbol-axis size")
    p.add_argument("--capacity", type=int, default=128, help="resting orders per side")
    p.add_argument("--batch", type=int, default=8, help="orders per symbol per dispatch")
    p.add_argument("--book-tiers", default=None, metavar="SPEC",
                   help="tiered book capacity classes: comma-separated "
                        "<count>x<capacity> groups partitioning the "
                        "symbol axis (one may use '*' for the remainder),"
                        " each optionally pinning symbols with "
                        ":SYM;SYM — e.g. '8x8192:HOT-0,56x1024,*x128'. "
                        "Unpinned symbols fill the last group first and "
                        "spill toward deeper groups. Full books are "
                        "metered backpressure (me_book_capacity_rejects_"
                        "total + per-tier high-watermark gauges). "
                        "Composes with --serve-shards (every count "
                        "divisible by K); refused with --native-lanes/"
                        "--mesh. The spec is part of checkpoint "
                        "compatibility: restoring under a different spec "
                        "falls back to full replay")
    p.add_argument("--engine-kernel", choices=("matrix", "sorted", "levels"),
                   default="matrix",
                   help="match formulation (engine/kernel.py matrix, "
                        "engine/kernel_sorted.py sorted, "
                        "engine/kernel_levels.py levels — all "
                        "oracle-parity; sorted is O(CAP) per order for "
                        "deep books, levels matches over price-level "
                        "FIFO rows so the sweep is O(levels) and deep "
                        "books stop costing what empty books cost)")
    p.add_argument("--window-ms", type=float, default=2.0, help="dispatch batching window")
    p.add_argument("--megadispatch-max-waves", type=int, default=1,
                   metavar="M",
                   help="coalesce up to M queued dispatch batches into ONE "
                        "stacked device scan when the queue is deep: one "
                        "XLA dispatch amortized over M waves, compacted "
                        "completion readback. Python path = "
                        "engine_runner._prepare_mega + the dispatcher's "
                        "adaptive controller; --native-lanes builds the "
                        "[M, S, B, 7] stacks and decodes the compacted "
                        "mega completions in C++ (me_lanes.cpp). 1 "
                        "(default) = off, exactly today's serial schedule; "
                        "output is bit-identical at any M. --mesh ignores "
                        "it")
    p.add_argument("--megadispatch-latency-us", type=float, default=5000.0,
                   metavar="US",
                   help="latency budget for the coalescing controller: M "
                        "is clamped so a stacked dispatch's estimated "
                        "turnaround (per-wave cost EMA x M) stays under "
                        "this many microseconds — deep queues amortize "
                        "dispatches without unbounded batching latency")
    p.add_argument("--pipeline-inflight", type=int, default=2,
                   help="staged-but-undecoded dispatches kept in flight "
                        "(decode stays FIFO; >1 hides the per-batch decode "
                        "sync round trip on a tunneled chip)")
    p.add_argument("--rpc-workers", type=int, default=256)
    p.add_argument("--checkpoint-dir", default=None,
                   help="enable periodic device-book checkpoints here")
    p.add_argument("--checkpoint-interval-s", type=float, default=30.0)
    p.add_argument("--no-native", action="store_true",
                   help="force the pure-Python runtime layer")
    p.add_argument("--native-lanes", action="store_true",
                   help="serve through the C++ lane engine "
                        "(native/me_lanes.cpp): lane build, host checks "
                        "and completion/storage decode run natively; "
                        "Python works per dispatch, not per op. "
                        "Single-device only (incompatible with --mesh)")
    p.add_argument("--profile-dir", default=None,
                   help="capture a jax.profiler device trace of the whole "
                        "serving session into this directory (TensorBoard)")
    p.add_argument("--trace-dir", default=None, metavar="DIR",
                   help="export sampled per-dispatch Chrome trace_event "
                        "JSON here (Perfetto / chrome://tracing loadable): "
                        "every Nth dispatch (--trace-sample) plus every "
                        "dispatch slower than the rolling p99, as nested "
                        "pipeline-stage slices with host spans and sink "
                        "commits on their own tracks. Bounded writer "
                        "queue; a full disk degrades to a rate-limited "
                        "warning + me_trace_write_errors_total, never a "
                        "stalled dispatch (omit to disable)")
    p.add_argument("--trace-sample", type=int, default=64, metavar="N",
                   help="uniform trace sampling interval for --trace-dir: "
                        "keep every Nth dispatch (slow outliers past the "
                        "rolling p99 are always kept; default 64)")
    p.add_argument("--busy-poll-us", type=float, default=0.0, metavar="US",
                   help="tail lever: spin this long before every condvar "
                        "wait on the dispatcher drain and the RPC "
                        "completion wait, trading CPU for queue-wakeup "
                        "scheduler latency (~tens of µs per hop in the "
                        "p99). Output is bit-identical to 0 (the "
                        "default, off); only worth enabling with spare "
                        "cores — see docs/BENCH_METHOD.md §tail-latency")
    p.add_argument("--book-cache-ms", type=float, default=0.0, metavar="MS",
                   help="tail lever: serve GetOrderBook from a conflated "
                        "latest-state cache with this TTL so book-read "
                        "bursts never contend the snapshot lock the "
                        "device step holds (staleness bounded by the "
                        "TTL; 0 = off, always live)")
    p.add_argument("--proto-reuse", action="store_true",
                   help="tail lever: recycle unary completion protos "
                        "per RPC thread instead of allocating per "
                        "response (stream events are never reused — "
                        "they alias subscriber queues and the feed "
                        "store)")
    p.add_argument("--metrics-port", type=int, default=None, metavar="PORT",
                   help="serve Prometheus text-format /metrics (+ /healthz, "
                        "/readyz, /flightrecorder) on this port from a "
                        "stdlib-only thread (0 = OS-assigned; omit to "
                        "disable). docs/OPERATIONS.md lists the metric "
                        "names")
    p.add_argument("--metrics-host", default="127.0.0.1", metavar="HOST",
                   help="bind address for --metrics-port (default loopback; "
                        "0.0.0.0 to expose to a scrape network)")
    p.add_argument("--flight-dir", default=None, metavar="DIR",
                   help="flight-recorder dump directory (default: "
                        "<db dir>/flight). Recent dispatch summaries dump "
                        "as JSON on SIGUSR2, fatal dispatch error, and "
                        "clean shutdown")
    p.add_argument("--feed-depth", type=int, default=1 << 16, metavar="N",
                   help="sequenced-feed retransmission ring depth per "
                        "(channel, key) domain — reconnecting stream "
                        "clients replay up to this many missed events via "
                        "resume_from_seq (docs/OPERATIONS.md 'Sequenced "
                        "feed'). 0 disables sequencing (legacy unsequenced "
                        "streams; max-throughput benches)")
    p.add_argument("--feed-spill-dir", default=None, metavar="DIR",
                   help="spill ring-evicted feed events to atomic segment "
                        "files here, extending the gap-fill window beyond "
                        "memory (off by default)")
    p.add_argument("--stream-queue", type=int, default=1024, metavar="N",
                   help="per-subscriber stream queue depth; overflow drops "
                        "oldest (counted as stream_dropped_events, "
                        "recoverable via the sequenced feed)")
    p.add_argument("--serve-shards", type=int, default=1, metavar="K",
                   help="partition serving into K independent symbol-"
                        "sharded lanes (server/shards.py): a symbol->shard "
                        "router at the edge, one ring+dispatcher+runner "
                        "column per shard (each pinned to its own device "
                        "when several are visible), strided order-id "
                        "allocation, per-lane checkpoints under "
                        "<dir>/shard-<i>. K must divide --symbols; "
                        "incompatible with --mesh (1 = off)")
    p.add_argument("--shard-devices", default="auto", metavar="POLICY",
                   help="with --serve-shards: lane->device placement "
                        "policy. 'auto' (default) round-robins lanes "
                        "across all visible devices when more than one "
                        "is visible; 'roundrobin' always places "
                        "explicitly (lane i -> device i%%D, even at "
                        "D=1); 'pinned:<o0,o1,...>' gives exactly one "
                        "device ordinal per lane (e.g. pinned:0,0,1,1). "
                        "Each lane's books and jit executables commit "
                        "to its device. See the OPERATIONS.md "
                        "compatibility matrix")
    p.add_argument("--feed-fanin", choices=("hub", "merged"),
                   default="hub",
                   help="with --serve-shards: feed publication topology. "
                        "'hub' (default, and the K=1 path) stamps every "
                        "lane's events under the one StreamHub lock; "
                        "'merged' gives each lane its own sequencer "
                        "domain (per-lane seq + venue epoch) feeding ONE "
                        "merger thread that enforces per-lane seq "
                        "contiguity (gap-fill aware, "
                        "me_feed_fanin_gaps_total) and delivers into "
                        "the hub — lanes stop serializing their publish "
                        "tails through the hub lock. Incompatible with "
                        "--gateway-addr/--standby")
    p.add_argument("--mesh", type=int, default=0, metavar="N",
                   help="shard the symbol axis over an N-device mesh "
                        "(0 = single device); N must divide --symbols")
    p.add_argument("--mesh-serve", action="store_true",
                   help="serve ONE mesh-sharded engine over ALL visible "
                        "devices (sugar for --mesh <device count>): the "
                        "serving dispatcher drives parallel/sharding.py's "
                        "ShardedEngine — one shard_map'd jit stepping "
                        "every device per dispatch. The measurable "
                        "counterpart to --serve-shards+--shard-devices "
                        "(K independent jits); see BENCH_METHOD "
                        "§device-sweep. Carries --mesh's compatibility "
                        "constraints")
    p.add_argument("--gateway-addr", default=None, metavar="HOST:PORT",
                   help="also serve through the C++ gRPC gateway on this "
                        "address (port 0 = OS-assigned)")
    p.add_argument("--audit", action="store_true",
                   help="online surveillance (matching_engine_tpu/audit/): "
                        "publish a sequenced drop-copy record per order "
                        "lifecycle event at the decode boundary (consume "
                        "via `client audit` or StreamOrderUpdates with "
                        "the reserved __dropcopy__ client id) and run the "
                        "in-process InvariantAuditor over them — legal "
                        "transitions, quantity conservation, fill "
                        "symmetry, seq continuity, crossed-TOB sanity, "
                        "sampled store<->feed equality. First violation "
                        "flight-dumps with the offending record; "
                        "me_audit_violations_total counts; /auditz turns "
                        "red (while /readyz stays up)")
    p.add_argument("--audit-sample", type=int, default=8, metavar="N",
                   help="audit cost bound: full shadow-state tracking for "
                        "a deterministic 1-in-N order subset (hash of "
                        "the OID number); the cheap per-record, seq, and "
                        "crossed-book invariants always run for ALL "
                        "orders. 1 = shadow everything (corruption "
                        "soaks/tests; default 8)")
    p.add_argument("--oplog-ship", action="store_true",
                   help="warm-standby replication, primary side "
                        "(matching_engine_tpu/replication/): republish "
                        "every admitted dispatch's ops as ONE sequenced "
                        "`oplog` feed event (flat op-record codec, "
                        "submits carry their assigned order ids) plus "
                        "periodic heartbeats, so a --standby replica can "
                        "apply the identical dispatch sequence. Needs "
                        "--feed-depth > 0 (the retransmission window is "
                        "the standby's catch-up budget; --feed-spill-dir "
                        "extends it); EngineOp dispatch routes only "
                        "(incompatible with --native-lanes and "
                        "--gateway-addr, whose ops bypass the shipper; "
                        "RunAuction/--auction-open refused — the uncross "
                        "is not replicated)")
    p.add_argument("--standby", default=None, metavar="HOST:PORT",
                   help="boot as a warm-standby replica of the primary at "
                        "this address: apply its sequenced op log "
                        "deterministically through this server's own "
                        "engine + SQLite sink, serve READ-ONLY (submits/"
                        "cancels/amends/auctions reject app-level; books, "
                        "streams, metrics serve), and continuously attest "
                        "store bit-identity against the primary's "
                        "drop-copy audit channel (primary must run "
                        "--audit for attestation; /replz reports). "
                        "Promote via the Promote RPC (`client promote`) "
                        "or --standby-auto-promote-s. Mirror the "
                        "primary's --symbols/--capacity/--batch/"
                        "--serve-shards exactly")
    p.add_argument("--standby-auto-promote-s", type=float, default=0.0,
                   metavar="SECS",
                   help="with --standby: self-promote when the primary's "
                        "oplog heartbeat lapses this long (0 = manual "
                        "promotion only, the default — split-brain "
                        "arbitration belongs to the operator or an "
                        "external lease, not a lone timeout)")
    p.add_argument("--standby-no-attest", action="store_true",
                   help="with --standby: replicate without attesting "
                        "(for a primary that runs --oplog-ship WITHOUT "
                        "--audit — there is no drop-copy channel to "
                        "attest against, so the attestor would only park "
                        "local rows and pump me_repl_attest_unmatched "
                        "at dispatch rate; /replz then reports "
                        "attested=0 by design)")
    p.add_argument("--auction-open", action="store_true",
                   help="boot in call-auction accumulation: submits REST "
                        "without matching until a RunAuction uncross opens "
                        "continuous trading (engine/auction.py)")
    p.add_argument("--shm-ingress", default=None, metavar="PATH",
                   help="zero-copy shared-memory ingress: create an oprec "
                        "ring segment at PATH (a co-located client writes "
                        "flat 384-byte records straight into the mapped "
                        "ring; server/shm_ingress.py polls, screens, and "
                        "dispatches them — no proto, no python per-op). "
                        "Put PATH on a ram-backed fs (/dev/shm) for the "
                        "zero-copy win")
    p.add_argument("--shm-slots", type=int, default=4096, metavar="N",
                   help="shm ingress request-ring slots (power of two)")
    p.add_argument("--shm-resp-slots", type=int, default=8192, metavar="N",
                   help="shm ingress response-ring slots (power of two)")
    p.add_argument("--shm-torn-ms", type=float, default=50.0, metavar="MS",
                   help="how long the shm poller waits for a claimed "
                        "slot's commit before recovering it as torn (a "
                        "writer SIGKILLed mid-record)")
    p.add_argument("--admission-rate", type=int, default=0, metavar="N",
                   help="admission screen: max ops per client per "
                        "--admission-window-s fixed window (0 = off); "
                        "vectorized, shared by every ingress path "
                        "(server/admission.py)")
    p.add_argument("--admission-window-s", type=float, default=1.0,
                   metavar="S",
                   help="admission rate-limit window seconds")
    p.add_argument("--admission-max-qty", type=int, default=0, metavar="N",
                   help="admission screen: per-op submit/amend quantity "
                        "cap below the engine maximum (0 = off)")
    p.add_argument("--admission-band-bps", type=int, default=0,
                   metavar="BPS",
                   help="admission screen: priced submits must land "
                        "within BPS basis points of the symbol's anchor "
                        "(last admitted priced submit; 0 = off)")
    p.add_argument("--admission-stp", action="store_true",
                   help="admission screen: reject submits that would "
                        "cross the client's own recently admitted "
                        "resting interest (window-scoped edge STP in "
                        "front of the engine's owner-lane STP)")
    args = p.parse_args(argv)

    # Persistent compile cache (same default as benchmarks/bench_child.py):
    # over the tunneled backend a cold compile costs tens of seconds per
    # (config, bucket) — a restarted or re-benched server must not pay it
    # twice. ME_JAX_CACHE overrides; empty disables.
    cache_dir = os.environ.get(
        "ME_JAX_CACHE",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))), ".jax_cache"),
    )
    if cache_dir:
        try:
            import jax

            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        except Exception:  # noqa: BLE001 — older jax: run uncached
            pass

    if args.mesh_serve:
        if args.mesh:
            config_error(
                "--mesh-serve with --mesh N",
                "--mesh-serve IS --mesh sized to every visible device",
                "--mesh-serve alone, or an explicit --mesh N")
            return 3
        if args.serve_shards > 1:
            config_error(
                "--mesh-serve with --serve-shards",
                "one meshed jit vs K independent jits: pick one cut",
                "--serve-shards K [--shard-devices POLICY] for "
                "partitioned lanes; --mesh-serve for the shard_map'd "
                "engine")
            return 3
        import jax

        args.mesh = len(jax.devices())
        print(f"[SERVER] --mesh-serve: meshing all "
              f"{args.mesh} visible device(s)")
    try:
        mesh = resolve_mesh(args.mesh, args.symbols)
    except ValueError as e:
        print(f"[SERVER] bad --mesh: {e}", file=sys.stderr)
        return 3
    if args.native_lanes and (mesh is not None or args.no_native):
        print("[SERVER] --native-lanes is single-device and needs the "
              "native runtime (drop --mesh/--no-native)", file=sys.stderr)
        return 3
    if args.shard_devices != "auto" and args.serve_shards <= 1:
        config_error(
            "--shard-devices without --serve-shards K>1",
            "placement policies place the K partitioned lanes",
            "--serve-shards K --shard-devices auto|roundrobin|"
            "pinned:<o0,..,oK-1>; --mesh-serve places via the mesh")
        return 3
    if args.serve_shards > 1:
        if mesh is not None:
            print("[SERVER] --serve-shards partitions host serving; it is "
                  "incompatible with --mesh (the ShardedEngine path)",
                  file=sys.stderr)
            return 3
        if args.symbols % args.serve_shards != 0:
            print(f"[SERVER] --symbols {args.symbols} not divisible by "
                  f"--serve-shards {args.serve_shards}", file=sys.stderr)
            return 3
        from matching_engine_tpu.server.shards import parse_shard_devices

        try:
            parse_shard_devices(args.shard_devices, args.serve_shards)
        except ValueError as e:
            print(f"[SERVER] bad --shard-devices: {e}", file=sys.stderr)
            return 3
        if args.native_lanes and args.gateway_addr is not None:
            config_error(
                "--serve-shards with --native-lanes and --gateway-addr",
                "the C++ gateway's native-lane drain is single-lane",
                "--serve-shards + --gateway-addr (python dispatch "
                "route); --serve-shards + --native-lanes on the "
                "grpcio/shm edges; --native-lanes + --gateway-addr "
                "single-lane")
            return 3
    if args.feed_fanin == "merged":
        if args.serve_shards <= 1:
            config_error(
                "--feed-fanin merged without --serve-shards K>1",
                "the merge exists to decouple K lanes' publish tails",
                "--feed-fanin merged with --serve-shards K>1; "
                "--feed-fanin hub at any K")
            return 3
        if args.gateway_addr is not None or args.standby:
            config_error(
                "--feed-fanin merged with --gateway-addr/--standby",
                "the gateway bridge and the standby applier publish "
                "through the hub directly, bypassing the merge",
                "--feed-fanin merged on a primary's grpcio/shm edges; "
                "--feed-fanin hub otherwise")
            return 3
    if args.oplog_ship or args.standby:
        if args.native_lanes or args.gateway_addr is not None \
                or mesh is not None:
            # The shipper re-encodes EngineOps at the drain loops; the
            # C++ lane/gateway drains and the mesh path never build them.
            print("[SERVER] replication (--oplog-ship/--standby) runs on "
                  "the EngineOp dispatch routes only: drop "
                  "--native-lanes/--gateway-addr/--mesh", file=sys.stderr)
            return 3
        if args.feed_depth == 0:
            print("[SERVER] replication needs the sequenced feed "
                  "(--feed-depth > 0)", file=sys.stderr)
            return 3
    if args.standby and args.auction_open:
        print("[SERVER] --standby is read-only; it cannot open a call "
              "period (--auction-open)", file=sys.stderr)
        return 3
    if args.oplog_ship and args.auction_open:
        print("[SERVER] --auction-open needs an uncross to open trading, "
              "and the auction uncross is not replicated on the op log "
              "(it bypasses the dispatcher drain loops the shipper rides) "
              "— drop one of the two flags", file=sys.stderr)
        return 3

    tiers, tier_pins = (), None
    if args.book_tiers:
        if args.native_lanes or mesh is not None:
            print("[SERVER] --book-tiers runs on the python dispatch "
                  "routes (composes with --serve-shards): drop "
                  "--native-lanes/--mesh", file=sys.stderr)
            return 3
        from matching_engine_tpu.server.tiered_runner import (
            parse_book_tiers,
        )

        try:
            tiers, tier_pins = parse_book_tiers(args.book_tiers,
                                                args.symbols)
        except ValueError as e:
            print(f"[SERVER] bad --book-tiers: {e}", file=sys.stderr)
            return 3
        cap = max(c for _, c in tiers)
        if args.capacity != cap and args.capacity != 128:
            print(f"[SERVER] note: --capacity {args.capacity} superseded "
                  f"by the deepest tier ({cap})")
    try:
        cfg = EngineConfig(
            num_symbols=args.symbols,
            capacity=max(c for _, c in tiers) if tiers else args.capacity,
            batch=args.batch, kernel=args.engine_kernel, tiers=tiers)
    except (AssertionError, ValueError) as e:
        print(f"[SERVER] bad engine config: {e}", file=sys.stderr)
        return 3
    flight_dir = args.flight_dir or os.path.join(
        os.path.dirname(os.path.abspath(args.db)), "flight")
    from matching_engine_tpu.server.admission import AdmissionConfig

    admission_cfg = AdmissionConfig(
        rate_limit=args.admission_rate or None,
        rate_window_s=args.admission_window_s,
        max_quantity=args.admission_max_qty or None,
        price_band_bps=args.admission_band_bps or None,
        stp=args.admission_stp)
    if not admission_cfg.any_enabled:
        admission_cfg = None
    try:
        server, port, parts = build_server(
            args.addr, args.db, cfg, window_ms=args.window_ms,
            rpc_workers=args.rpc_workers,
            checkpoint_dir=args.checkpoint_dir,
            checkpoint_interval_s=args.checkpoint_interval_s,
            native=not args.no_native,
            mesh=mesh,
            gateway_addr=args.gateway_addr,
            pipeline_inflight=args.pipeline_inflight,
            native_lanes=args.native_lanes,
            flight_dir=flight_dir,
            feed_depth=args.feed_depth,
            feed_spill_dir=args.feed_spill_dir,
            stream_maxsize=args.stream_queue,
            serve_shards=args.serve_shards,
            megadispatch_max_waves=args.megadispatch_max_waves,
            megadispatch_latency_us=args.megadispatch_latency_us,
            busy_poll_us=args.busy_poll_us,
            book_cache_ms=args.book_cache_ms,
            proto_reuse=args.proto_reuse,
            trace_dir=args.trace_dir,
            trace_sample_every=args.trace_sample,
            audit=args.audit,
            audit_sample=args.audit_sample,
            oplog_ship=args.oplog_ship,
            standby_addr=args.standby,
            standby_auto_promote_s=args.standby_auto_promote_s,
            standby_attest=not args.standby_no_attest,
            tier_pins=tier_pins,
            admission_cfg=admission_cfg,
            shm_ingress_path=args.shm_ingress,
            shm_slots=args.shm_slots,
            shm_resp_slots=args.shm_resp_slots,
            shm_torn_ms=args.shm_torn_ms,
            shard_devices=args.shard_devices,
            feed_fanin=args.feed_fanin,
        )
    except SystemExit as e:
        return int(e.code or 3)

    if args.auction_open:
        # A call period is venue-wide: with partitioned serving it opens
        # on every lane (ServingShards fans the flip out).
        target = parts.get("shards") or parts["runner"]
        try:
            target.set_auction_mode(True)
        except ValueError as e:  # venue-depth capacity: no call periods
            print(f"[SERVER] --auction-open refused: {e}", file=sys.stderr)
            shutdown(server, parts)
            return 3
        target.flush_auction_mode()
        print("[SERVER] auction call period OPEN (submits rest unmatched "
              "until an all-symbols RunAuction)")

    stop_evt = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop_evt.set())
    # SIGUSR2 -> flight-recorder JSON dump (operator post-mortem on a
    # live server; no drain, no lock acquisition).
    parts["recorder"].install_sigusr2()

    server.start()
    print(f"[SERVER] listening on port {port} "
          f"(symbols={cfg.num_symbols} capacity={cfg.capacity} batch={cfg.batch})")
    obs = None
    try:
        if args.metrics_port is not None:
            try:
                obs = ObsServer(
                    parts["metrics"], recorder=parts["recorder"],
                    ready_fn=lambda: not stop_evt.is_set(),  # 503 in drain
                    port=args.metrics_port, host=args.metrics_host,
                    auditor=parts["auditor"],
                    repl=parts.get("replica") or parts.get("oplog"),
                )
            except OSError as e:
                # Bind failures land AFTER the gRPC edges went live; the
                # finally below still drains them cleanly. Same typed
                # exit as a gRPC bind failure.
                print(f"[SERVER] failed to bind metrics port "
                      f"{args.metrics_port}: {e}", file=sys.stderr)
                return 2
            obs.start()
            print(f"[SERVER] metrics on port {obs.port} "
                  f"(/metrics /healthz /readyz /flightrecorder)")
        with trace(args.profile_dir) if args.profile_dir else contextlib.nullcontext():
            stop_evt.wait()
        return 0
    finally:
        print("[SERVER] shutting down")
        # Shutdown BEFORE closing the obs endpoint: /readyz answers 503
        # (and /healthz 200) throughout the grace drain, so a balancer
        # sees the documented not-ready signal instead of conn-refused.
        shutdown(server, parts)
        if obs is not None:
            obs.close()


if __name__ == "__main__":
    sys.exit(main())
