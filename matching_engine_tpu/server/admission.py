"""Vectorized per-client admission screens — edge risk control at codec
speed (ROADMAP Open item 3: "admission control runs at codec speed, not
RPC speed").

`record_flaws` (domain/oprec.py) is the structural screen: everything
decidable from one record alone. This module layers the PER-CLIENT
production screens a million-user edge implies — rate limiting, max
order size, price banding, self-trade prevention — as numpy batch
passes over the same record arrays, shared by every bulk ingress path:
the shm ring poller (server/shm_ingress.py), SubmitOrderBatch and
SubmitOrderStream (server/service.py), and the C++ gateway's batch verb
(which forwards into the same service handler). The per-op RPCs run the
identical rules through `screen_one` (a 1-record batch), so admission
is venue-wide consistent.

Semantics — BATCH-BOUNDARY, deliberately, so every screen stays a pure
vector pass with no per-op python:

- rate limit: a fixed window of `rate_window_s` seconds per client id.
  EVERY structurally-clean op counts toward the window, admitted or not
  (abuse spends budget); within a batch the count is cumulative, so op
  k of one client's burst is op `pre + k` of its window.
- max order size: submits and amends with quantity above the configured
  per-client cap reject. (record_flaws already enforces the ENGINE cap;
  this is the venue's risk knob below it.)
- price band: priced submits must land within `price_band_bps` of the
  symbol's ANCHOR — the last admitted priced submit's price as of batch
  entry (the first priced submit for a symbol sets the anchor and
  passes). Anchors update once per batch, after screening.
- self-trade prevention: a submit that would CROSS the client's own
  resting opposite-side interest rejects. The screen tracks its own
  window-scoped table of admitted GTC LIMIT submits per
  (client, symbol): best own bid / best own ask, expiring `stp_ttl_s`
  after the last insert. Frozen at batch entry, updated after — a
  conservative EDGE screen in front of the engine's owner-lane STP, not
  a book-exact guarantee (documented in OPERATIONS.md).

Reject reasons are REASON_* codes (domain/oprec.py — the MeIngressReason
vocabulary shared with the shm response ring and the C++ structural
screen); RPC paths render them through REASON_MESSAGES.

tests/test_admission.py pins the vectorized passes against an
independent per-op python oracle over property-fuzzed flows.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

import numpy as np

from matching_engine_tpu.domain.oprec import (
    OPREC_AMEND,
    OPREC_SUBMIT,
    REASON_BAND,
    REASON_MESSAGES,
    REASON_QTY,
    REASON_RATE,
    REASON_STP,
)

# Collapsed device codes that carry a price (LIMIT / LIMIT_IOC /
# LIMIT_FOK) and the one that RESTS (GTC LIMIT) — proto.collapse_otype.
_PRICED_OTYPES = (0, 2, 3)
_RESTING_OTYPE = 0


@dataclass(frozen=True)
class AdmissionConfig:
    """One knob per screen; None/0 disables that screen. A config with
    every screen disabled makes AdmissionScreens.enabled False and
    screen() a no-op."""
    rate_limit: int | None = None     # clean ops per client per window
    rate_window_s: float = 1.0
    max_quantity: int | None = None   # per-op submit/amend size cap
    price_band_bps: int | None = None  # band around the symbol anchor
    stp: bool = False
    stp_ttl_s: float = 5.0            # own-quote table entry lifetime

    @property
    def any_enabled(self) -> bool:
        return bool(self.rate_limit or self.max_quantity
                    or self.price_band_bps or self.stp)


class AdmissionScreens:
    """The shared, thread-safe screen state. One instance per server;
    callers from any ingress thread (rpc handlers, the shm poller, the
    stream drain) serialize on one lock per BATCH — the per-op cost is
    the numpy pass, never the lock."""

    def __init__(self, cfg: AdmissionConfig, metrics=None):
        self.cfg = cfg
        self.enabled = cfg.any_enabled
        self.metrics = metrics
        if metrics is not None and self.enabled:
            # Register the literal zeros (PR 8 convention) so a scrape
            # shows the reject-by-reason series from boot.
            for name in ("admission_rate_rejects", "admission_qty_rejects",
                         "admission_band_rejects", "admission_stp_rejects"):
                metrics.inc(name, 0)
        self._lock = threading.Lock()
        # rate: client bytes -> ops counted in the current fixed window.
        self._rate_counts: dict[bytes, int] = {}
        self._rate_window_start = 0.0
        # price band: symbol bytes -> last admitted priced-submit price.
        self._anchors: dict[bytes, int] = {}
        # stp: (client, symbol) bytes -> [max own bid, min own ask,
        # expiry stamp] from admitted GTC LIMIT submits.
        self._stp: dict[tuple[bytes, bytes], list] = {}

    # -- the vectorized pass ------------------------------------------------

    def screen(self, arr: np.ndarray, flaws: list, now: float | None = None
               ) -> np.ndarray:
        """Run every enabled screen over the structurally-clean records
        (flaws[i] is None). Returns a per-record uint8 REASON_* array
        (0 = admitted) and fills the corresponding `flaws` slots with
        the reason messages, positionally — the record_flaws contract
        extended."""
        n = len(arr)
        reasons = np.zeros(n, dtype=np.uint8)
        if not self.enabled or n == 0:
            return reasons
        clean = np.fromiter((f is None for f in flaws), dtype=bool, count=n)
        idx = np.nonzero(clean)[0]
        if len(idx) == 0:
            return reasons
        sub = arr[idx]
        if now is None:
            now = time.monotonic()
        cfg = self.cfg
        with self._lock:
            rej = np.zeros(len(idx), dtype=np.uint8)
            if cfg.rate_limit:
                self._screen_rate(sub, rej, now)
            if cfg.max_quantity:
                self._screen_qty(sub, rej)
            if cfg.price_band_bps:
                self._screen_band(sub, rej)
            if cfg.stp:
                self._screen_stp(sub, rej, now)
            # State updates see only ADMITTED records (batch-boundary
            # semantics: screens above read the pre-batch tables).
            ok = rej == 0
            if cfg.price_band_bps:
                self._update_anchors(sub[ok])
            if cfg.stp:
                self._update_stp(sub[ok], now)
        reasons[idx] = rej
        hit = np.nonzero(rej)[0]
        for j in hit:
            flaws[idx[j]] = REASON_MESSAGES[int(rej[j])]
        if self.metrics is not None and len(hit):
            m = self.metrics
            counts = np.bincount(rej[hit], minlength=6)
            if counts[REASON_RATE]:
                m.inc("admission_rate_rejects", int(counts[REASON_RATE]))
            if counts[REASON_QTY]:
                m.inc("admission_qty_rejects", int(counts[REASON_QTY]))
            if counts[REASON_BAND]:
                m.inc("admission_band_rejects", int(counts[REASON_BAND]))
            if counts[REASON_STP]:
                m.inc("admission_stp_rejects", int(counts[REASON_STP]))
        return reasons

    def screen_one(self, op: int, side: int, otype: int, price_q4: int,
                   quantity: int, symbol: bytes, client_id: bytes,
                   now: float | None = None) -> str | None:
        """The per-op RPCs' entry: a 1-record batch through the same
        vector pass (SubmitOrder/CancelOrder/AmendOrder call this so the
        per-op edge obeys the same rules as the bulk paths)."""
        if not self.enabled:
            return None
        from matching_engine_tpu.domain import oprec

        # Clamp identifiers to the record boxes: Cancel/Amend reach here
        # with only a non-empty check behind them, and an oversized id
        # must screen (by its box-sized prefix), not raise out of the
        # RPC as a transport error. It can't own anything either way —
        # the directory lookup downstream still answers it.
        arr = oprec.pack_records(
            [(op, side, otype, price_q4, quantity,
              symbol[:oprec.SYMBOL_BYTES],
              client_id[:oprec.CLIENT_ID_BYTES], b"")])
        flaws: list = [None]
        self.screen(arr, flaws, now=now)
        return flaws[0]

    # -- individual screens (lock held, clean records only) ------------------

    def _rotate_rate_window(self, now: float) -> None:
        if now - self._rate_window_start >= self.cfg.rate_window_s:
            self._rate_counts.clear()
            self._rate_window_start = now

    def _screen_rate(self, sub: np.ndarray, rej: np.ndarray,
                     now: float) -> None:
        self._rotate_rate_window(now)
        limit = self.cfg.rate_limit
        cids = sub["client_id"]
        uniq, inv, counts = np.unique(cids, return_inverse=True,
                                      return_counts=True)
        # Rank of each record within its client's run of this batch
        # (stable sort -> 0..count-1 per group, in record order).
        order = np.argsort(inv, kind="stable")
        starts = np.zeros(len(uniq), dtype=np.int64)
        np.cumsum(counts[:-1], out=starts[1:])
        ranks = np.empty(len(sub), dtype=np.int64)
        ranks[order] = np.arange(len(sub)) - np.repeat(starts, counts)
        pre = np.fromiter(
            (self._rate_counts.get(u.tobytes(), 0) for u in uniq),
            dtype=np.int64, count=len(uniq))
        over = (pre[inv] + ranks) >= limit
        rej[over & (rej == 0)] = REASON_RATE
        # Every clean op spends budget, admitted or not.
        for u, c in zip(uniq, counts):
            key = u.tobytes()
            self._rate_counts[key] = self._rate_counts.get(key, 0) + int(c)

    def _screen_qty(self, sub: np.ndarray, rej: np.ndarray) -> None:
        sized = ((sub["op"] == OPREC_SUBMIT) | (sub["op"] == OPREC_AMEND))
        over = sized & (sub["quantity"] > self.cfg.max_quantity)
        rej[over & (rej == 0)] = REASON_QTY

    def _screen_band(self, sub: np.ndarray, rej: np.ndarray) -> None:
        bps = self.cfg.price_band_bps
        priced = ((sub["op"] == OPREC_SUBMIT)
                  & np.isin(sub["otype"], _PRICED_OTYPES))
        pidx = np.nonzero(priced)[0]
        if len(pidx) == 0:
            return
        syms = sub["symbol"][pidx]
        anchors = np.fromiter(
            (self._anchors.get(s.tobytes(), 0) for s in syms),
            dtype=np.int64, count=len(pidx))
        prices = sub["price_q4"][pidx].astype(np.int64)
        # |p - anchor| * 10000 > bps * anchor, integer exact; anchor 0 =
        # no anchor yet, passes (and sets it in the update pass).
        out = (anchors > 0) & (np.abs(prices - anchors) * 10000
                               > bps * anchors)
        tgt = pidx[out]
        rej[tgt[rej[tgt] == 0]] = REASON_BAND

    def _update_anchors(self, admitted: np.ndarray) -> None:
        priced = ((admitted["op"] == OPREC_SUBMIT)
                  & np.isin(admitted["otype"], _PRICED_OTYPES))
        recs = admitted[priced]
        # Last admitted priced submit per symbol wins: iterate in order,
        # one dict store per record run (unique symbols per batch).
        for s, p in zip(recs["symbol"], recs["price_q4"]):
            self._anchors[s.tobytes()] = int(p)

    def _screen_stp(self, sub: np.ndarray, rej: np.ndarray,
                    now: float) -> None:
        submits = np.nonzero(sub["op"] == OPREC_SUBMIT)[0]
        if len(submits) == 0:
            return
        recs = sub[submits]
        quotes = np.zeros((len(submits), 2), dtype=np.int64)  # [bid, ask]
        have = np.zeros(len(submits), dtype=bool)
        for j, (c, s) in enumerate(zip(recs["client_id"], recs["symbol"])):
            q = self._stp.get((c.tobytes(), s.tobytes()))
            if q is not None and q[2] > now:
                quotes[j] = (q[0], q[1])
                have[j] = True
        prices = recs["price_q4"].astype(np.int64)
        is_buy = recs["side"] == 1
        is_mkt = np.isin(recs["otype"], (1, 4))
        own_bid, own_ask = quotes[:, 0], quotes[:, 1]
        # A buy crosses own resting ask at price >= ask; a sell crosses
        # own resting bid at price <= bid; a MARKET order crosses any
        # opposite-side own quote.
        cross = have & np.where(
            is_buy,
            (own_ask > 0) & (is_mkt | (prices >= own_ask)),
            (own_bid > 0) & (is_mkt | (prices <= own_bid)))
        tgt = submits[np.nonzero(cross)[0]]
        rej[tgt[rej[tgt] == 0]] = REASON_STP

    def _update_stp(self, admitted: np.ndarray, now: float) -> None:
        resting = ((admitted["op"] == OPREC_SUBMIT)
                   & (admitted["otype"] == _RESTING_OTYPE))
        recs = admitted[resting]
        expiry = now + self.cfg.stp_ttl_s
        for r in recs:
            key = (r["client_id"].tobytes(), r["symbol"].tobytes())
            q = self._stp.get(key)
            if q is None or q[2] <= now:
                q = [0, 0, expiry]
                self._stp[key] = q
            price = int(r["price_q4"])
            if int(r["side"]) == 1:
                q[0] = max(q[0], price)
            else:
                q[1] = min(q[1], price) if q[1] else price
            q[2] = expiry
        # Opportunistic expiry sweep, bounded: drop dead entries once the
        # table outgrows a soft cap so it can't accrete unboundedly.
        if len(self._stp) > 65536:
            self._stp = {k: v for k, v in self._stp.items() if v[2] > now}
