"""EngineRunner: the single owner of device book state and host directories.

Bridges the host order world (string symbols, "OID-n" ids, client ids,
statuses) and the device world (symbol slots, int oids, [S, B] dispatches).
One runner instance is driven by exactly one dispatcher thread, so device
state and the directories need no locking on the hot path; read-only RPC
views (book snapshots) take the snapshot lock.

Responsibilities per dispatch:
- group validated ops into dense OrderBatches (order-preserving per symbol),
- run the jit'd engine step (book state stays on device, donated),
- decode results/fills into: per-op outcomes, maker bookkeeping, storage
  events, per-client order updates, and top-of-book market data.

Reference parity notes: order ids are "OID-<monotonic>" resumed from storage
(matching_engine_service.cpp:29-32, storage.cpp:254-268); statuses are the
proto OrderUpdate.Status machine.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import deque

import jax
import numpy as np

from matching_engine_tpu.engine.book import EngineConfig, OrderBatch, init_book
from matching_engine_tpu.engine.harness import (
    PIPELINE_DEPTH,
    HostOrder,
    batch_view,
    build_batch_arrays,
    decode_step_packed,
    run_pipelined,
)
from matching_engine_tpu.engine.kernel import (
    BUY,
    CANCELED,
    FILLED,
    NEW,
    OP_AMEND,
    OP_CANCEL,
    OP_REST,
    OP_SUBMIT,
    PARTIALLY_FILLED,
    REJECTED,
    SELL,
    engine_step_packed,
)
from matching_engine_tpu.domain.order import owner_hash
from matching_engine_tpu.proto import MARKET_FOK, pb2
from matching_engine_tpu.storage.storage import FillRow
from matching_engine_tpu.utils.metrics import Metrics, Timer
from matching_engine_tpu.utils.obs import warn_rate_limited
from matching_engine_tpu.utils.tracing import step_annotation


@dataclasses.dataclass
class OrderInfo:
    """Host directory entry for one accepted order.

    `oid` is the unbounded host order number ("OID-<oid>" — a Python int,
    int64+ safe). `handle` is the order's *device* identity: a recycled
    int32 drawn from the runner's allocator, unique among live orders only.
    The device book/fill lanes stay int32 (TPU-native lane width) no matter
    how many orders the server has ever seen; the host maps handle->info.
    """

    oid: int
    order_id: str
    client_id: str
    symbol: str
    side: int
    otype: int
    price_q4: int
    quantity: int
    remaining: int
    status: int
    handle: int = 0


@dataclasses.dataclass
class EngineOp:
    """One validated operation headed for the device."""

    op: int                      # OP_SUBMIT / OP_REST / OP_CANCEL / OP_AMEND
    info: OrderInfo              # the order (submit) or the target (cancel/amend)
    cancel_requester: str = ""   # client asking for the cancel
    amend_qty: int = 0           # OP_AMEND: the new (reduced) quantity


@dataclasses.dataclass
class OpOutcome:
    op: EngineOp
    status: int
    filled: int
    remaining: int
    error: str = ""


@dataclasses.dataclass
class DispatchResult:
    outcomes: list[OpOutcome]
    order_updates: list[pb2.OrderUpdate]
    market_data: list[pb2.MarketDataUpdate]
    storage_orders: list[tuple]
    storage_updates: list[tuple]
    storage_fills: list[FillRow]
    fill_count: int


def _prefetch_host(item) -> None:
    """Start the decode readback's device->host copy NOW (async).

    A staged wave's output is read back as np.asarray(out.small) at decode
    time — on a tunneled chip that sync bills a full network round trip.
    Issuing copy_to_host_async at STAGE time overlaps the transfer with
    the host's batching of newer work, so a pipelined decode finds the
    bytes already landed. Items are (..., out) for the packed dense and
    sparse shapes (both expose .small); the mesh StepOutput has no packed
    vector and decodes from addressable shards — skipped."""
    small = getattr(item[-1], "small", None)
    if small is not None:
        try:
            small.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass  # backend without async host copies: decode pays the sync


class _Staged:
    """One dispatch's in-flight state between stage (device waves issued)
    and finish (decode + publish + eviction). `deferred` means every wave
    is already dispatched and `items` holds their undecoded outputs."""

    __slots__ = ("ops", "by_handle", "res", "terminal_makers",
                 "dispatch_iter", "decode_fn", "finalize_fn", "items",
                 "deferred", "timeline")

    def __init__(self, ops, by_handle, res, terminal_makers, dispatch_iter,
                 decode_fn, finalize_fn, deferred, timeline=None):
        self.ops = ops
        self.by_handle = by_handle
        self.res = res
        self.terminal_makers = terminal_makers
        self.dispatch_iter = dispatch_iter
        self.decode_fn = decode_fn
        self.finalize_fn = finalize_fn
        self.items: deque = deque()
        self.deferred = deferred
        self.timeline = timeline  # utils/obs.DispatchTimeline | None


class EngineRunner:
    """Owns the device books + host order directories.

    With `mesh` set, the books are symbol-sharded over the device mesh and
    every step runs through the shard_map'd path (parallel/sharding.py) —
    the serving stack above (dispatcher, service, storage, streams,
    checkpoints) is identical either way, because all host-side reads go
    through np.asarray on logical arrays.
    """

    def __init__(self, cfg: EngineConfig, metrics: Metrics | None = None,
                 mesh=None, hub=None, pipeline_inflight: int = 2,
                 oid_offset: int = 0, oid_stride: int = 1, device=None,
                 owns_filter=None, megadispatch_max_waves: int = 1):
        self.cfg = cfg
        # Megadispatch (single-device dense path only): stack up to this
        # many [S, B, 7] waves per device call and run ONE jit'd lax.scan
        # over them (kernel.engine_step_mega) — one XLA dispatch amortized
        # over the stack, with device-side completion compaction bounding
        # the readback to O(real ops). 1 (the default) keeps today's
        # serial per-wave schedule exactly; any value is bit-identical to
        # it by construction (tests/test_megadispatch.py).
        self.megadispatch_max_waves = max(1, int(megadispatch_max_waves))
        self.metrics = metrics or Metrics()
        self._snapshot_lock = threading.Lock()
        # Held for a FULL dispatch (device step + host directory mutation);
        # checkpointing acquires it to get an untorn book+directory snapshot.
        self._dispatch_lock = threading.Lock()
        self._id_lock = threading.Lock()  # oid/symbol assignment from RPC threads
        self._step_num = 0  # device-trace step annotation counter
        if mesh is not None:
            from matching_engine_tpu.parallel.multihost import local_symbol_slice
            from matching_engine_tpu.parallel.sharding import ShardedEngine

            self._sharded = ShardedEngine(cfg, mesh)
            self.book = self._sharded.init_book()
            # Slot ALLOCATION is confined to the rows on this host's own
            # devices; symbol OWNERSHIP (which host may book a name) is the
            # separate owns_symbol() hash check — slots recycle, names don't.
            sl = local_symbol_slice(mesh, cfg.num_symbols)
            self._slot_lo, self._slot_hi = sl.start, sl.stop
            self._n_hosts = jax.process_count()
            self._host = jax.process_index()
        else:
            self._sharded = None
            if cfg.tiers:
                # Tiered capacity classes: the TieredEngineRunner subclass
                # owns one book PER TIER (server/tiered_runner.py); a
                # single [S, max_capacity] book here would allocate
                # exactly the memory the tiers exist to avoid.
                assert type(self).__name__ != "EngineRunner", \
                    "a tiered EngineConfig needs TieredEngineRunner"
                self.book = None
            else:
                self.book = init_book(cfg)
                if device is not None:
                    # Partitioned serving (server/shards.py): pin this
                    # lane's books to one device. The book is COMMITTED
                    # there, so every jit'd step (whose other inputs are
                    # host numpy) runs on — and donates back to — that
                    # device; K lanes on K chips dispatch with no
                    # collectives between them.
                    self.book = jax.device_put(self.book, device)
            self._slot_lo, self._slot_hi = 0, cfg.num_symbols
            self._n_hosts, self._host = 1, 0
        self.device = device
        # Symbol-shard ownership override (server/shards.py): when serving
        # as one of K partitioned lanes, owns_symbol delegates here so the
        # recovery/restore replay and the edge checks all route by the
        # same shard cut. None = the multi-host name-hash rule.
        self._owns_filter = owns_filter
        # Directories (host truth mirroring device state).
        self.symbols: dict[str, int] = {}           # symbol -> slot
        self.slot_symbols: list[str | None] = [None] * cfg.num_symbols
        self.orders_by_handle: dict[int, OrderInfo] = {}
        self.orders_by_id: dict[str, OrderInfo] = {}
        # Order-ID allocation: lane i of K partitioned serving lanes
        # allocates the strided residue class {offset+1, offset+1+K, ...}
        # so IDs stay globally unique across lanes with no cross-lane
        # lock, and (oid-1) % stride recovers the birth lane. The default
        # (offset 0, stride 1) is the reference's dense "OID-<n>" line.
        self.oid_offset = oid_offset
        self.oid_stride = max(1, oid_stride)
        self.next_oid_num = oid_offset + 1
        # Device-handle allocator: handles recycle when orders go terminal,
        # so the int32 lane space can never wrap no matter the order count
        # (live handles are bounded by open + in-flight orders).
        self._next_handle = 1            # 0 = empty lane, never allocated
        self._free_handles: list[int] = []
        # Per-slot live (open or in-flight) order counts; a slot whose count
        # returns to 0 is recycled, so the symbol axis bounds *concurrent*
        # symbols, not lifetime-distinct ones.
        self._slot_live = [0] * cfg.num_symbols
        self._free_slots: list[int] = []
        self._next_slot = self._slot_lo
        # Durability-gap ledger: (order_id, kind, lost_qty) tuples recorded
        # when fill RECORDS are lost (kernel max_fills overflow) while the
        # book state applied them. Drained into the durable store's `recon`
        # table at the next checkpoint (utils/checkpoint.py) so the audit
        # can hold exact arithmetic even across an acknowledged loss.
        # Bounded: without a checkpoint daemon nothing drains it, and a
        # sustained-overflow server must not leak memory — the overflow of
        # the ledger itself is counted and the tail dropped.
        self.pending_recon: list[tuple[str, str, int]] = []
        self._recon_cap = 100_000
        # Self-trade-prevention identity registry (ADVICE r3): every
        # client id gets a COLLISION-FREE int32 owner id — owner_hash is
        # only the first candidate; a clash probes to the next free id.
        # Assignments persist at first sight (pending_owner_ids drains to
        # the durable owner_ids table via flush_owner_ids, outside the
        # dispatch lock) so identities are stable across restarts — a
        # hash-colliding pair must not swap identities depending on
        # post-restart arrival order while checkpointed book lanes still
        # carry the old ints.
        self._owner_by_client: dict[str, int] = {}
        self._owner_claimed: dict[int, str] = {}
        self._owner_registry_cap = 1_000_000
        self.pending_owner_ids: list[tuple[str, int]] = []
        # Serializes flush_owner_ids callers (drain loop, idle wakeup,
        # auction, checkpoint daemon, recovery) against each other.
        # Producers append under the dispatch lock and are NOT required
        # to hold this one: the flush only ever mutates the list
        # IN PLACE (del prefix / insert front), so a concurrent append —
        # atomic under the GIL, always at the tail — can never be lost
        # the way the old swap-rebind could drop it (ADVICE r4 medium).
        self._owner_flush_lock = threading.Lock()
        self.persist_owner_ids = None  # callable(list) -> bool | None
        # Call-auction accumulation mode: while True, both serving edges
        # submit orders as OP_REST (rest without matching — books may
        # stand crossed) and MARKET orders are rejected; a RunAuction
        # uncross clears the flag (the opening cross). Toggled at boot
        # (--auction-open), restored from the durable store on restart,
        # or left False for pure continuous trading. Change the flag via
        # set_auction_mode so the serving stack's persistence callback
        # (build_server wires storage.set_meta) records it — a restart
        # must resume an open call period even when no book happens to
        # stand crossed.
        self.auction_mode = False
        self.persist_auction_mode = None  # callable(bool) -> bool | None
        self._mode_dirty = False
        # Cross-dispatch pipelining: a bounded FIFO of staged-but-undecoded
        # dispatches with their finish callbacks (see dispatch_pipelined).
        # Depth >1 lets the drain loop accept several batches between
        # decode syncs — on a tunneled chip each decode sync bills a
        # network round trip, and ONE pending max meant every second batch
        # ate a full RTT head-of-line (r3's 40x p50->p99 serving tail).
        self._pending: deque[tuple[_Staged, object]] = deque()
        self._pipeline_inflight = max(1, int(pipeline_inflight))
        # Per-runner dispatched-op odometer (plain GIL-atomic int): the
        # partitioned-serving sampler (server/shards.py) attributes rate
        # and imbalance per lane from it — the shared Metrics registry
        # aggregates across lanes and can't.
        self.ops_dispatched = 0
        # Constructor-wired (build_server passes the StreamHub the
        # dispatchers publish to): lets the decode skip CONSTRUCTING stream
        # protos (per-fill OrderUpdates, per-symbol MarketDataUpdates) when
        # no subscriber exists — the common serving case. None = always
        # build (library/test use reads DispatchResult directly).
        self.hub = hub
        # --audit drop-copy publisher (audit/dropcopy.py), wired by
        # build_server: auctions publish their fills/updates through it
        # too, and the gateway bridge reads it per routed lane.
        self.dropcopy = None

    def place_book(self, host_book) -> None:
        """Install a host-side BookBatch as the live device book, honoring
        the runner's sharding (checkpoint restore path)."""
        if self._sharded is not None:
            from matching_engine_tpu.parallel import hostlocal

            self.book = hostlocal.put_tree(
                host_book, self._sharded.book_sharding)
        else:
            self.book = jax.device_put(host_book)

    # -- id/symbol management ---------------------------------------------

    def assign_oid(self) -> tuple[int, str]:
        with self._id_lock:
            n = self.next_oid_num
            self.next_oid_num += self.oid_stride
        return n, f"OID-{n}"

    def seed_oid_sequence(self, next_n: int) -> None:
        """Advance the OID line past `next_n` (storage resume). A strided
        lane additionally rounds UP to its own residue class, so reseeding
        from a store written at any other shard count (including 1) keeps
        every future ID unique and lane-attributable."""
        with self._id_lock:
            n = max(self.next_oid_num, next_n)
            n += (self.oid_offset - (n - 1)) % self.oid_stride
            self.next_oid_num = max(self.next_oid_num, n)

    def assign_handle(self) -> int:
        """A device handle unique among live orders (recycled int32)."""
        with self._id_lock:
            if self._free_handles:
                return self._free_handles.pop()
            h = self._next_handle
            if h >= 2**31:
                # Unreachable in practice: reached only if >2^31 handles
                # leak without recycling. Fail loudly, never wrap the lane.
                raise RuntimeError("device handle space exhausted")
            self._next_handle += 1
            return h

    def _release_handle(self, h: int) -> None:
        if h:
            with self._id_lock:
                self._free_handles.append(h)

    def release_unqueued(self, info: OrderInfo) -> None:
        """Recycle the handle + slot live-count of a submit that is KNOWN to
        have never entered the dispatch queue (RingFull reject). The device
        never saw the handle and no directory entry exists, so recycling is
        safe; without this, sustained ring-full overload leaks one handle
        and one slot live-count per reject (ADVICE r2)."""
        self._release_handle(info.handle)
        # Our un-dropped live count pins the symbol->slot mapping.
        slot = self.symbols.get(info.symbol)
        if slot is not None:
            self._slot_release(slot)

    def symbol_slot(self, symbol: str) -> int | None:
        """Existing slot, or allocate one; None when the symbol axis is full
        of symbols that still have live orders (empty slots are recycled)."""
        with self._id_lock:
            return self._slot_locked(symbol)

    def _slot_locked(self, symbol: str) -> int | None:
        slot = self.symbols.get(symbol)
        if slot is not None:
            return slot
        if self._free_slots:
            slot = self._free_slots.pop()
        elif self._next_slot < self._slot_hi:
            slot = self._next_slot
            self._next_slot += 1
        else:
            return None
        self.symbols[symbol] = slot
        self.slot_symbols[slot] = symbol
        return slot

    def rebuild_slot_allocator(self) -> None:
        """Recompute the slot allocator from the (restored) symbol
        directory — checkpoint restore path. The tiered runner overrides
        with its per-group allocators."""
        self._next_slot = max(
            self._slot_lo, 1 + max(self.symbols.values(), default=-1))
        self._free_slots = [
            s for s in range(self._slot_lo, self._next_slot)
            if self.slot_symbols[s] is None
        ]

    def owns_all_symbols(self) -> bool:
        """True when every symbol is homed on this runner (single process,
        no shard filter) — lets the batch edge skip the per-op ownership
        check instead of paying per-record python on the path built to
        avoid it. Sharded lanes route by the same hash before dispatch,
        so their groups satisfy the filter by construction."""
        return self._owns_filter is None and self._n_hosts == 1

    def owns_symbol(self, symbol: str) -> bool:
        """True when this host is the symbol's home (multi-process routing
        invariant). Slots are recycled, so ownership must be decided by
        NAME, not slot availability — otherwise two hosts could each book
        the same symbol and diverge. Always True single-process."""
        if self._owns_filter is not None:
            return self._owns_filter(symbol)
        if self._n_hosts == 1:
            return True
        from matching_engine_tpu.parallel.multihost import symbol_home

        return symbol_home(symbol, self._n_hosts) == self._host

    def slot_acquire(self, symbol: str) -> int | None:
        """Allocate/find the symbol's slot AND count one live order on it.

        The submit path must use this (not symbol_slot) so a slot can never
        be recycled between RPC validation and dispatch. Paired with the
        release in the dispatch's terminal-eviction pass.
        """
        with self._id_lock:
            slot = self._slot_locked(symbol)
            if slot is not None:
                self._slot_live[slot] += 1
            return slot

    def _slot_release(self, slot: int) -> None:
        """One live order on `slot` went terminal; recycle the slot when its
        book is empty (count 0 == no resting or in-flight orders — the
        device lanes for it are all qty==0 by the masking invariant)."""
        with self._id_lock:
            self._slot_live[slot] -= 1
            if self._slot_live[slot] == 0:
                sym = self.slot_symbols[slot]
                if sym is not None:
                    del self.symbols[sym]
                    self.slot_symbols[slot] = None
                    self._recycle_slot(slot)

    def _recycle_slot(self, slot: int) -> None:
        """Return a freed slot to its allocator free list (id lock held).
        The tiered runner overrides: the slot goes back to its GROUP's
        free list, not the flat one."""
        self._free_slots.append(slot)

    # -- the dispatch ------------------------------------------------------

    def run_dispatch(self, ops: list[EngineOp]) -> DispatchResult:
        """Apply ops to the device books and decode all consequences."""
        posts: list = []
        with self._dispatch_lock, Timer(self.metrics, "engine_dispatch_us"):
            self._finish_pending_locked(posts)
            result = self._run_dispatch_locked(ops)
        for p in posts:
            p()
        self.flush_owner_ids()
        return result

    # -- cross-dispatch pipelining ----------------------------------------
    #
    # The serving drain loops overlap consecutive dispatches: a NEW
    # batch's device waves are dispatched first (they chain after older
    # staged waves on the donated book), and decodes happen later — each
    # staged output completed on device (and its host copy landed, via
    # _prefetch_host) while the host was batching newer work, so the
    # decode sync costs the residual, not a full round trip. Up to
    # `pipeline_inflight` dispatches stay staged, each pinning its wave
    # outputs in HBM (bounded by PIPELINE_DEPTH waves apiece); a new
    # dispatch finishes only the overflow beyond that window. Decode/
    # publish order stays strictly FIFO (older batches fully decoded and
    # published before newer ones), so directory mutations, storage rows,
    # and stream events are identical to the serial schedule. Idle
    # wakeup, checkpoint quiesce, auctions, run_dispatch, and shutdown
    # drain the WHOLE queue.

    @property
    def has_pending(self) -> bool:
        return bool(self._pending)

    def sync_directory_for_snapshot_locked(self) -> None:
        """Quiesce-point hook (dispatch lock held, pending FIFO drained):
        make the Python directories authoritative before a state snapshot.
        No-op here — the Python path's directories are always live; the
        native lane runner refreshes its mirror from the C++ engine."""

    def finish_pending(self) -> None:
        """Decode+publish ALL pending dispatches, oldest first (idle
        wakeup / shutdown path)."""
        posts: list = []
        with self._dispatch_lock:
            self._finish_pending_locked(posts)
        for p in posts:
            p()
        self.flush_owner_ids()

    def _finish_pending_locked(self, posts: list) -> None:
        """Lock held. Drains the WHOLE pending FIFO (quiesce semantics:
        auction, checkpoint, run_dispatch, shutdown, idle wakeup all need
        fully-decoded directories). Each callback publishes under the lock
        and may return a thunk (future/tag completions) the caller must
        run AFTER release."""
        while self._pending:
            self._finish_oldest_locked(posts)

    def _finish_oldest_locked(self, posts: list) -> None:
        """Lock held. Finishes the OLDEST pending dispatch only — the
        pipelined serving path's per-batch finisher (FIFO decode order;
        newer batches stay staged so their device waves keep overlapping
        host work)."""
        if not self._pending:
            return
        staged, cb = self._pending.popleft()
        self.metrics.set_gauge("inflight_dispatches", len(self._pending))
        try:
            result = self._finish_locked(staged)
            err = None
        except BaseException as e:  # noqa: BLE001 — the failed batch must
            # not poison the CURRENT caller (it belongs to a previous drain
            # iteration); _finish_locked already rolled back registrations.
            # (dispatch_errors is counted ONCE, by the edge callback —
            # that counter is the alert signal; the log line is for the
            # human and rate-limits like every sink/hub failure print: a
            # persistently-failing device would otherwise spam stdout at
            # batch frequency exactly when the operator needs it.)
            warn_rate_limited(
                "runner-pending",
                f"[runner] pending dispatch failed: {type(e).__name__}: {e}")
            result, err = None, e
        post = cb(result, err)
        if post is not None:
            posts.append(post)

    def dispatch_pipelined(self, ops: list[EngineOp], on_finish,
                           timeline=None) -> None:
        """Serving-loop entry: dispatch `ops`, overlapping with the
        previous batch's decode. `on_finish(result, error)` runs under the
        dispatch lock when this batch's results are decoded (publish to
        sink/hub there); its return value, if not None, is a thunk the
        runner invokes after releasing the lock (client completions).
        `timeline` (utils/obs.DispatchTimeline) is stamped at the stage
        ledger's build/issue/decode boundaries; the edge finishes it."""
        self._dispatch_common(
            lambda: self._stage_locked(ops, timeline=timeline), on_finish)

    def _dispatch_common(self, stage, on_finish) -> None:
        """The serving-dispatch orchestration shared by every entry
        (EngineOp batches here, raw record batches in the native lane
        runner): lock discipline, pipeline-FIFO overflow, post-lock
        completion thunks. `stage()` runs under the dispatch lock and
        returns the staged batch."""
        posts: list = []
        with self._dispatch_lock, Timer(self.metrics, "engine_dispatch_us"):
            try:
                staged = stage()
            except BaseException as e:  # noqa: BLE001 — fail THIS batch,
                # keep the loop; the previous batch is still finished below.
                self._finish_pending_locked(posts)
                post = on_finish(None, e)
                if post is not None:
                    posts.append(post)
                for p in posts:
                    p()
                return
            if staged.deferred:
                self._pending.append((staged, on_finish))
                self.metrics.set_gauge("inflight_dispatches",
                                       len(self._pending))
                # Finish only the overflow beyond the inflight window:
                # batches decode strictly FIFO, but up to
                # `pipeline_inflight` stay staged so their (already
                # host-copy-prefetched) outputs land while the host
                # batches newer work.
                while len(self._pending) > self._pipeline_inflight:
                    self._finish_oldest_locked(posts)
            else:
                # Ineligible for deferral (more waves than the
                # HBM-bounded window): drain everything pending, then
                # finish this batch too — same as the serial schedule.
                self._finish_pending_locked(posts)
                try:
                    result = self._finish_locked(staged)
                    err = None
                except BaseException as e:  # noqa: BLE001
                    result, err = None, e
                post = on_finish(result, err)
                if post is not None:
                    posts.append(post)
        for p in posts:
            p()
        self.flush_owner_ids()

    def _rollback_registrations(self, ops, res: DispatchResult) -> None:
        # A prep/dispatch/decode failure leaves undecoded ops maybe-applied
        # on device. Their handles are NOT recycled (service-layer policy
        # for maybe-enqueued ops) — but the eager directory entries must
        # go, restoring the pre-registration state: no outcome => no
        # directory row.
        done = {id(o.op) for o in res.outcomes}
        for e in ops:
            if e.op in (OP_SUBMIT, OP_REST) and id(e) not in done:
                self.orders_by_handle.pop(e.info.handle, None)
                self.orders_by_id.pop(e.info.order_id, None)

    def _run_dispatch_locked(self, ops: list[EngineOp]) -> DispatchResult:
        return self._finish_locked(self._stage_locked(ops, defer=False))

    def _stage_locked(self, ops: list[EngineOp], defer: bool = True,
                      timeline=None):
        """Build + register + (when deferrable) dispatch all device waves
        WITHOUT decoding. Returns a _Staged; _finish_locked completes it."""
        res = DispatchResult([], [], [], [], [], [], 0)
        # Sampled once per dispatch: a subscriber attaching mid-dispatch
        # just misses this dispatch (same as attaching a moment later).
        self._build_ou = self.hub is None or self.hub.has_order_update_subs()
        self._build_md = self.hub is None or self.hub.has_market_data_subs()
        host_orders = []
        # handle -> FIFO of this batch's ops on that handle: several ops
        # may target one order in one dispatch (amend then cancel is a
        # routine client sequence), and device result rows for a symbol
        # arrive in enqueue order — a plain dict would misattribute every
        # result to the LAST op on the handle.
        by_handle: dict[int, deque[EngineOp]] = {}
        terminal_makers: set[int] = set()
        try:
            for e in ops:
                i = e.info
                if e.op in (OP_CANCEL, OP_AMEND) and i.status in (
                        FILLED, CANCELED, REJECTED):
                    # The target went terminal (and its handle was recycled)
                    # after this cancel was enqueued — a device cancel now
                    # could hit an unrelated order reusing the handle.
                    # Reject on the host; the device never sees a stale
                    # handle.
                    res.outcomes.append(
                        OpOutcome(e, REJECTED, 0, 0, "order not open"))
                    continue
                slot = self.symbols[i.symbol]  # caller guarantees allocation
                # Auction-mode classification happens HERE, under the
                # dispatch lock — never at the RPC edge. RunAuction holds
                # the same lock when it flips auction_mode off, so a queued
                # submit can never dispatch as OP_REST after the uncross
                # opened continuous trading (or vice versa). In the call
                # period MARKET submits also rest-classify: the kernel
                # cancels their remainder (no maker scan runs), which is
                # the correct no-liquidity-view outcome for one that slips
                # past the edge validation in the mode-flip race window.
                dev_op = e.op
                if dev_op == OP_SUBMIT and self.auction_mode:
                    dev_op = OP_REST
                host_orders.append(
                    HostOrder(
                        sym=slot,
                        op=dev_op,
                        side=i.side,
                        otype=i.otype,
                        price=i.price_q4,
                        qty=(e.amend_qty if e.op == OP_AMEND
                             else i.remaining if e.op != OP_CANCEL else 0),
                        oid=i.handle,
                        # Self-trade prevention identity travels to the
                        # device book lanes with every submit/rest.
                        owner=self._owner_for(i.client_id),
                    )
                )
                by_handle.setdefault(i.handle, deque()).append(e)
                if e.op in (OP_SUBMIT, OP_REST):
                    # Register BEFORE dispatch: with waves dispatched ahead
                    # of the decode cursor, a concurrent book_snapshot can
                    # see device lanes whose wave hasn't decoded yet — any
                    # lane visible on device must already have a directory
                    # entry or the snapshot would silently omit acked
                    # resting orders. (_decode_batch's re-insert of the
                    # same OrderInfo object is a no-op.)
                    self.orders_by_handle[i.handle] = i
                    self.orders_by_id[i.order_id] = i

            n_waves, dispatch_iter, decode_fn, finalize_fn = self._prepare(
                ops, host_orders, by_handle, res, terminal_makers,
                timeline=timeline)
            if timeline is not None:
                timeline.waves = n_waves
                timeline.stamp_build()
            staged = _Staged(ops, by_handle, res, terminal_makers,
                             dispatch_iter, decode_fn, finalize_fn,
                             deferred=False, timeline=timeline)
            if defer and n_waves <= PIPELINE_DEPTH:
                # Dispatch every wave now, decode later (all deployment
                # shapes — the mesh decode reads addressable shards, so
                # deferral is as safe as on a single device): the staged
                # outputs are HBM-bounded by the wave-count cap.
                for item in dispatch_iter:
                    staged.items.append(item)
                    _prefetch_host(item)
                staged.deferred = True
                if timeline is not None:
                    timeline.stamp_issue()
            return staged
        except BaseException:
            self._rollback_registrations(ops, res)
            raise

    def _finish_locked(self, staged) -> DispatchResult:
        try:
            if staged.deferred:
                while staged.items:
                    staged.decode_fn(staged.items.popleft())
            else:
                run_pipelined(staged.dispatch_iter, staged.decode_fn)
            staged.finalize_fn()
        except BaseException:
            self._rollback_registrations(staged.ops, staged.res)
            raise
        self._evict_terminal(staged.ops, staged.res, staged.by_handle,
                             staged.terminal_makers)
        self.metrics.inc("dispatches")
        self.metrics.inc("engine_ops", len(staged.ops))
        self.metrics.inc("fills", staged.res.fill_count)
        self.ops_dispatched += len(staged.ops)
        if staged.timeline is not None:
            # Decode boundary: results + fills decoded, directories
            # updated, terminal orders evicted — the dispatch's host tail.
            staged.timeline.stamp_decode()
            staged.timeline.counters = {
                "ops": len(staged.ops),
                "fills": staged.res.fill_count,
                "outcomes": len(staged.res.outcomes),
            }
        return staged.res

    def _prepare(self, ops, host_orders, by_handle,
                 res: DispatchResult, terminal_makers: set[int],
                 timeline=None):
        """Build the (n_waves, dispatch_iter, decode_fn, finalize_fn)
        quadruple for this dispatch's shape. Nothing executes until the
        dispatch iterator is pulled; finalize_fn runs after the last wave
        decodes (market-data publication)."""
        # Sparse dispatch: when the batch is far below grid capacity (the
        # common serving case), ship O(ops) lanes instead of the dense
        # [S, B] planes — the host<->device transfer is the serving path's
        # latency-critical boundary (engine/sparse.py). Bit-identical to
        # the dense step (tests/test_sparse.py).
        use_sparse = (
            self._sharded is None
            and host_orders
            and len(host_orders) * 4 <= self.cfg.num_symbols * self.cfg.batch
        )
        if use_sparse:
            from matching_engine_tpu.engine.sparse import (
                build_sparse,
                decode_sparse_step,
                engine_step_sparse,
            )

            self.metrics.inc("sparse_dispatches")
            if timeline is not None:
                timeline.shape = "sparse"
            tob: dict[int, tuple] = {}
            built = build_sparse(self.cfg, host_orders)

            def decode_sparse(item):
                sparse, nreal, out = item
                results, fills, overflow, dec = decode_sparse_step(
                    sparse, nreal, out)
                self.metrics.inc(
                    "readback_bytes",
                    out.small.size * 4
                    + (out.fills.size * 4
                       if dec.fill_count > dec.fills_inline.shape[1] else 0))
                self._account(results, fills, overflow, by_handle, res,
                              terminal_makers)
                if self._build_md:
                    # Later waves overwrite: a symbol untouched by the last
                    # wave keeps its (still-current) earlier top-of-book.
                    # All host numpy (decoded from the one packed read).
                    sl = sparse.slot[:nreal].tolist()
                    bb = dec.tob_best_bid[:nreal].tolist()
                    bs = dec.tob_bid_size[:nreal].tolist()
                    ba = dec.tob_best_ask[:nreal].tolist()
                    asz = dec.tob_ask_size[:nreal].tolist()
                    for i in range(nreal):
                        tob[sl[i]] = (bb[i], bs[i], ba[i], asz[i])

            def dispatch_sparse():
                for sparse, nreal in built:
                    self._step_num += 1
                    with self._snapshot_lock, step_annotation(
                            "engine_step_sparse", self._step_num):
                        self.book, out = engine_step_sparse(
                            self.cfg, self.book, sparse)
                    yield sparse, nreal, out

            def finalize_sparse():
                if self._build_md:
                    for s, (b_, bs_, a_, as_) in tob.items():
                        sym = self.slot_symbols[s]
                        if sym is None:
                            continue
                        res.market_data.append(pb2.MarketDataUpdate(
                            symbol=sym, best_bid=b_, best_ask=a_, scale=4,
                            bid_size=bs_, ask_size=as_,
                        ))

            return len(built), dispatch_sparse(), decode_sparse, finalize_sparse

        if host_orders:
            self.metrics.inc("dense_dispatches")
        arrays = build_batch_arrays(self.cfg, host_orders)
        if (self._sharded is None and self.megadispatch_max_waves > 1
                and len(arrays) > 1):
            return self._prepare_mega(arrays, by_handle, res,
                                      terminal_makers, timeline=timeline)
        if timeline is not None:
            timeline.shape = "mesh" if self._sharded is not None else "dense"
        touched_syms: set[int] = set()
        last_out = None  # StepOutput (mesh) or DenseDecoded (1-device)

        def account_dense(results, fills, overflow, out):
            nonlocal last_out
            last_out = out
            self._account(results, fills, overflow, by_handle, res,
                          terminal_makers)
            touched_syms.update(r.sym for r in results)

        if self._sharded is not None:

            def dispatch_dense():
                for arr in arrays:
                    self._step_num += 1
                    batch = batch_view(arr)
                    dev_batch = self._sharded.place_orders(batch)
                    with self._snapshot_lock, step_annotation("engine_step", self._step_num):
                        self.book, out = self._sharded.step(
                            self.book, dev_batch)
                    yield batch, out

            def decode_dense(item):
                # Decode from the HOST batch: its op/oid arrays are what
                # decode reads, and pulling the device copy back would
                # cost two cross-shard gathers per step for unchanged
                # data.
                batch, out = item
                account_dense(*self._sharded.decode(batch, out), out)
        else:
            # Packed single-device steps: one [S, B, 7] upload and one
            # small-vector readback each (+ a fill fetch only past the
            # inline segment) — transfer ROUND TRIPS, not just bytes,
            # bound tunneled serving latency.

            def dispatch_dense():
                for arr in arrays:
                    self._step_num += 1
                    with self._snapshot_lock, step_annotation("engine_step", self._step_num):
                        self.book, pout = engine_step_packed(
                            self.cfg, self.book, arr)
                    yield arr, pout

            def decode_dense(item):
                arr, pout = item
                results, fills, overflow, out = decode_step_packed(
                    self.cfg, batch_view(arr), pout)
                self.metrics.inc(
                    "readback_bytes",
                    pout.small.size * 4
                    + (pout.fills.size * 4
                       if out.fill_count > out.fills_inline.shape[1] else 0))
                account_dense(results, fills, overflow, out)

        def finalize_dense():
            if last_out is not None and touched_syms and self._build_md:
                self._market_data(last_out, touched_syms, res)

        return len(arrays), dispatch_dense(), decode_dense, finalize_dense

    def _prepare_mega(self, arrays, by_handle, res: DispatchResult,
                      terminal_makers: set[int], timeline=None):
        """The megadispatch dispatch shape: chunk the dispatch's waves
        into stacks of up to megadispatch_max_waves, run each stack
        through kernel.engine_step_mega's single lax.scan on the donated
        book, and decode the compacted readback wave-by-wave in order —
        so every host consequence (directory mutations, storage rows,
        stream events, eviction order) is bit-identical to the serial
        per-wave schedule (tests/test_megadispatch.py pins it on both
        kernels). Each staged item pins one stack's outputs in HBM, the
        same total as the serial waves it replaces, so the PIPELINE_DEPTH
        deferral bound keeps its meaning unchanged."""
        from matching_engine_tpu.engine import kernel as _kernel
        from matching_engine_tpu.engine.harness import decode_step_mega

        self.metrics.inc("dense_dispatches")
        m_cap = self.megadispatch_max_waves
        if timeline is not None:
            timeline.shape = "mega"
            timeline.mega_m = min(m_cap, len(arrays))
        chunks = [arrays[i:i + m_cap] for i in range(0, len(arrays), m_cap)]
        touched_syms: set[int] = set()
        last_dec: list = [None]

        def dispatch_mega():
            for group in chunks:
                m = len(group)
                # The host built the lane arrays, so every wave's real-op
                # count is known exactly: the compacted-completion buffer
                # (bucketed) can never truncate.
                rcap = _kernel.mega_result_cap(
                    self.cfg,
                    max(int(np.count_nonzero(a[:, :, 0])) for a in group))
                stacked = np.stack(group)
                self._step_num += 1
                with self._snapshot_lock, step_annotation(
                        "engine_step_mega", self._step_num):
                    self.book, mout = _kernel.engine_step_mega(
                        self.cfg, self.book, stacked, rcap)
                self.metrics.inc("megadispatch_steps")
                self.metrics.inc("megadispatch_stacked_waves", m)
                yield m, rcap, mout

        def decode_mega(item):
            m, rcap, mout = item
            waves, dec, fetched_full = decode_step_mega(
                self.cfg, mout, m, rcap)
            self.metrics.inc(
                "readback_bytes",
                mout.small.size * 4
                + (mout.fills.size * 4 if fetched_full else 0))
            for results, fills, overflow in waves:
                self._account(results, fills, overflow, by_handle, res,
                              terminal_makers)
                touched_syms.update(r.sym for r in results)
            last_dec[0] = dec

        def finalize_mega():
            # MegaDecoded carries the FINAL book's top-of-book — identical
            # to the serial schedule's last-wave market data.
            if last_dec[0] is not None and touched_syms and self._build_md:
                self._market_data(last_dec[0], touched_syms, res)

        return len(arrays), dispatch_mega(), decode_mega, finalize_mega

    # -- call auction ------------------------------------------------------

    def run_auction(self, symbols=None, sink=None) -> dict:
        """Call-auction uncross (engine/auction.py) over `symbols` (names;
        None/empty = every symbol currently allocated on this host).

        Serialized with dispatches on the dispatch lock (finishing any
        pipelined pending batch first — the auction must see fully-decoded
        directories); storage/stream events publish under the lock, same
        checkpoint invariant as a dispatch. Returns a summary dict with
        ALL of: "crossed" [(symbol, clearing_price_q4, executed)],
        "aborted" (any shard hit the all-or-nothing overflow), "error"
        (non-empty => the REQUEST failed: every requested symbol sat on
        an aborted shard; success=false at the RPC), "warning" (partial
        mesh abort: some shards uncrossed, the aborted shards' symbols
        are untouched and the call period, if open, stays open)."""
        posts: list = []
        try:
            with self._dispatch_lock, Timer(self.metrics,
                                            "engine_dispatch_us"):
                self._finish_pending_locked(posts)
                summary = self._run_auction_locked(symbols, sink)
                # Auctions are scheduled venue maintenance points and the
                # pipeline is drained here — the second rebase hook for
                # deployments running without a checkpoint daemon (one
                # [S] readback per auction; no-op below the threshold).
                self.maybe_rebase_seqs()
        finally:
            for p in posts:
                p()
            # Durable mode write OUTSIDE the dispatch lock (see
            # flush_auction_mode): a sqlite busy-wait here must not stall
            # order dispatch.
            self.flush_auction_mode()
            self.flush_owner_ids()
        return summary

    def run_auction_phased(self, decide, sink=None) -> dict:
        """Two-phase cross-lane uncross, driven by the serving shard
        barrier (server/shards.py): quiesce this lane under its dispatch
        lock, snapshot books, run the device uncross (prepare), then call
        `decide(ok, error)` — the barrier's vote-and-wait, which returns
        True only when EVERY lane prepared cleanly. On True the prepared
        uncross commits exactly like run_auction; on False the book
        snapshot is restored, leaving the lane bit-identical to never
        having auctioned (all-or-nothing ACROSS lanes, the cross-lane
        analogue of the kernel's per-shard all-or-nothing). Always
        all-symbols: the barrier exists for venue-wide uncross points."""
        posts: list = []
        summary = None
        try:
            with self._dispatch_lock, Timer(self.metrics,
                                            "engine_dispatch_us"):
                self._finish_pending_locked(posts)
                try:
                    prep = self.auction_prepare(None)
                except Exception as e:
                    # Vote abort BEFORE propagating so peer lanes are
                    # released from the barrier rather than timing out.
                    decide(False, f"{type(e).__name__}: {e}")
                    raise
                err = prep["error"]
                if decide(not err, err):
                    summary = self.auction_commit(prep, sink)
                    self.maybe_rebase_seqs()
                else:
                    self.auction_abort(prep)
                    summary = {"crossed": [], "aborted": True,
                               "error": err or "cross-lane barrier abort",
                               "warning": ""}
        finally:
            for p in posts:
                p()
            self.flush_auction_mode()
            self.flush_owner_ids()
        return summary

    def auction_prepare(self, symbols) -> dict:
        """Barrier phase 1 (call under the dispatch lock with the pipeline
        drained): snapshot books, then run the device uncross and abort
        analysis WITHOUT any host/directory mutation. The returned prep
        dict feeds exactly one of auction_commit / auction_abort."""
        saved = self._auction_books_copy()
        prep = self._auction_prepare_locked(symbols)
        prep["saved_books"] = saved
        return prep

    def auction_commit(self, prep, sink=None) -> dict:
        """Barrier phase 2a: apply the prepared uncross's host mutations
        (directories, storage rows, stream/drop-copy publishes, metrics)
        and drop the book snapshot. Same summary shape as run_auction."""
        prep.pop("saved_books", None)
        return self._auction_commit_locked(prep, sink)

    def auction_abort(self, prep) -> None:
        """Barrier phase 2b: restore the pre-auction book snapshot so the
        lane is bit-identical to never having auctioned. Directories were
        never touched (prepare is mutation-free), so only device state
        rolls back."""
        saved = prep.pop("saved_books", None)
        if saved is not None:
            with self._snapshot_lock:
                self._auction_books_restore(saved)

    def _copy_book_tree(self, tree):
        """Deep (host round-trip) copy of a book pytree. A plain
        device_put of a device array may ALIAS the source buffers, and
        the auction step DONATES the live book — the snapshot must own
        distinct memory or the restore would resurrect deleted buffers.
        Auctions are rare control-plane ops; one [S]-book round trip is
        acceptable."""
        def _copy(leaf):
            host = np.asarray(leaf)
            try:
                # Preserves placement for both single-device (committed
                # lane) and mesh-sharded leaves.
                return jax.device_put(host, leaf.sharding)
            except (AttributeError, ValueError):
                dev = getattr(self, "device", None)
                return (jax.device_put(host, dev) if dev is not None
                        else jax.device_put(host))
        return jax.tree_util.tree_map(_copy, tree)

    def _auction_books_copy(self):
        with self._snapshot_lock:
            return self._copy_book_tree(self.book)

    def _auction_books_restore(self, saved) -> None:
        # Caller holds _snapshot_lock (auction_abort).
        self.book = saved

    def _run_auction_locked(self, symbols, sink) -> dict:
        prep = self._auction_prepare_locked(symbols)
        if prep["error"]:
            return {"crossed": [], "aborted": prep["aborted"],
                    "error": prep["error"], "warning": ""}
        return self._auction_commit_locked(prep, sink)

    def _auction_prepare_locked(self, symbols) -> dict:
        from matching_engine_tpu.engine.book import auction_capacity_max

        if self.cfg.capacity > auction_capacity_max(self.cfg.kernel):
            # Defensive: unreachable for every EngineConfig the
            # constructor admits (matrix <= 1024 < 1073; sorted <= 8192
            # with the wide-sum uncross) — kept so a future capacity
            # bump cannot silently run a wrapping uncross.
            return {"symbols": symbols, "aborted": False,
                    "error": f"call auction unsupported at capacity "
                             f"{self.cfg.capacity} (kernel "
                             f"{self.cfg.kernel}); max supported is "
                             f"{auction_capacity_max(self.cfg.kernel)}"}
        mask = np.zeros((self.cfg.num_symbols,), dtype=bool)
        with self._id_lock:
            allocated = list(self.symbols.items())
        wanted = set(symbols) if symbols else None
        for name, slot in allocated:
            if wanted is None or name in wanted:
                mask[slot] = True
        self._build_ou = self.hub is None or self.hub.has_order_update_subs()
        self._build_md = self.hub is None or self.hub.has_market_data_subs()

        self._step_num += 1
        (lo, clear_price, executed, best_bid, bid_size, best_ask, ask_size,
         fills, aborted_shards, slot_aborted) = self._auction_device(mask)

        if aborted_shards:
            self.metrics.inc("auction_aborts", aborted_shards)
            # The REQUEST fails outright when every requested symbol sat
            # on an aborted shard — the caller's uncross did nothing.
            requested_slots = [s for n, s in allocated
                               if wanted is None or n in wanted]
            if requested_slots and all(
                    slot_aborted(s) for s in requested_slots):
                return {"symbols": symbols, "aborted": True,
                        "error": "fill buffer too small for the uncross "
                                 "(raise max_fills)"}
        return {"symbols": symbols, "aborted": aborted_shards > 0,
                "error": "", "lo": lo, "clear_price": clear_price,
                "executed": executed, "best_bid": best_bid,
                "bid_size": bid_size, "best_ask": best_ask,
                "ask_size": ask_size, "fills": fills,
                "aborted_shards": aborted_shards}

    def _auction_commit_locked(self, prep, sink) -> dict:
        from matching_engine_tpu.server.dispatcher import publish_result

        symbols = prep["symbols"]
        lo, fills = prep["lo"], prep["fills"]
        clear_price, executed = prep["clear_price"], prep["executed"]
        best_bid, bid_size = prep["best_bid"], prep["bid_size"]
        best_ask, ask_size = prep["best_ask"], prep["ask_size"]
        aborted_shards = prep["aborted_shards"]

        res = DispatchResult([], [], [], [], [], [], len(fills))
        touched: dict[int, OrderInfo] = {}
        for f in fills:
            bid = self.orders_by_handle.get(f.taker_oid)
            ask = self.orders_by_handle.get(f.maker_oid)
            for info in (bid, ask):
                if info is None:
                    continue  # unreachable if directories are consistent
                info.remaining -= f.quantity
                info.status = (FILLED if info.remaining == 0
                               else PARTIALLY_FILLED)
                touched[info.handle] = info
                if self._build_ou:
                    res.order_updates.append(
                        self._fill_update(info, f.price_q4, f.quantity))
            if bid is not None and ask is not None:
                res.storage_fills.append(
                    FillRow(bid.order_id, ask.order_id, f.price_q4,
                            f.quantity))
        # One final-state storage update per touched order (records within
        # one auction all execute at the same engine time).
        for info in touched.values():
            res.storage_updates.append(
                (info.order_id, info.status, info.remaining))

        crossed = []
        for i in np.nonzero(executed > 0)[0]:
            slot = lo + int(i)  # local block row -> global slot
            sym = self.slot_symbols[slot]
            if sym is None:
                continue
            crossed.append((sym, int(clear_price[i]), int(executed[i])))
            if self._build_md:
                res.market_data.append(pb2.MarketDataUpdate(
                    symbol=sym,
                    best_bid=int(best_bid[i]),
                    best_ask=int(best_ask[i]),
                    scale=4,
                    bid_size=int(bid_size[i]),
                    ask_size=int(ask_size[i]),
                ))
        for info in list(touched.values()):
            if info.remaining == 0:
                self._evict(info)
        if self.dropcopy is not None:
            # Auction executions are lifecycle events like any other:
            # the uncross's fills/updates ride the same drop-copy line
            # (no timeline — auctions are control-plane dispatches).
            # Before the sink sees the row lists (snapshot rule).
            self.dropcopy.publish(res, timeline=None, shape="auction")
        publish_result(res, sink, self.hub, self.metrics)
        self.metrics.inc("auctions")
        self.metrics.inc("auction_fills", len(fills))
        if symbols is None and aborted_shards == 0:
            # Only a FULLY-successful all-symbols uncross ends the call
            # period: a per-symbol auction — or an all-symbols one where
            # any shard aborted — must not open continuous trading while
            # books somewhere still stand crossed and unopened.
            self.set_auction_mode(False)
        warning = ""
        if aborted_shards:
            # Mesh partial abort: the overflowing shard(s) kept their
            # symbols untouched (per-shard all-or-nothing); the rest
            # uncrossed normally — success with a warning, and the call
            # period (if open) stays open for the untouched books.
            warning = (f"{aborted_shards} shard(s) aborted the uncross "
                       f"(fill log too small; raise max_fills) — their "
                       f"symbols are untouched"
                       + ("; auction call period stays OPEN"
                          if self.auction_mode else ""))
        return {"crossed": crossed, "aborted": aborted_shards > 0,
                "error": "", "warning": warning}

    def _auction_device(self, mask):
        """The auction's device step + raw decode (refactored hook so the
        tiered runner can run one uncross per tier group): returns
        (lo, clear_price, executed, best_bid, bid_size, best_ask,
        ask_size, fills, aborted_shards, slot_aborted) where the [.]
        arrays cover this host's local symbol block starting at `lo` and
        slot_aborted(slot) reports whether the shard/tier owning a global
        slot hit the all-or-nothing overflow."""
        if self._sharded is not None:
            with self._snapshot_lock, step_annotation("auction_step",
                                                      self._step_num):
                # Assign under the snapshot lock: the input book was
                # DONATED, so a concurrent snapshot reader between the
                # step and the assignment would touch deleted buffers.
                self.book, out = self._sharded.auction(self.book, mask)
            view, fills, aborted_shards = self._sharded.decode_auction(out)
            lo = view["lo"]
            clear_price, executed = view["clear_price"], view["executed"]
            best_bid, bid_size = view["best_bid"], view["bid_size"]
            best_ask, ask_size = view["best_ask"], view["ask_size"]
            aborted_flags = view["aborted_flags"]
            shard_lo = view["shard_lo"]
            local_syms = self._sharded.local_cfg.num_symbols
        else:
            from matching_engine_tpu.engine.auction import (
                auction_step,
                decode_auction,
            )

            with self._snapshot_lock, step_annotation("auction_step",
                                                      self._step_num):
                # Same donation rule as the mesh branch: assign in-lock.
                self.book, out = auction_step(self.cfg, self.book, mask)
            dec, fills = decode_auction(self.cfg, out)
            aborted_shards = 1 if dec.aborted else 0
            lo = 0
            clear_price, executed = dec.clear_price, dec.executed
            best_bid, bid_size = dec.best_bid, dec.bid_size
            best_ask, ask_size = dec.best_ask, dec.ask_size
            aborted_flags = np.array([dec.aborted])
            shard_lo = 0
            local_syms = self.cfg.num_symbols

        def slot_aborted(slot: int) -> bool:
            i = slot // local_syms - shard_lo
            return bool(0 <= i < len(aborted_flags) and aborted_flags[i])

        return (lo, clear_price, executed, best_bid, bid_size, best_ask,
                ask_size, fills, aborted_shards, slot_aborted)

    def _evict_terminal(self, ops, res: DispatchResult, by_handle,
                        terminal_makers: set[int]) -> None:
        # Evict terminal orders from the directories: once FILLED / CANCELED /
        # REJECTED an order can never be referenced by a later fill, book
        # snapshot, or legitimate cancel ("unknown order id" and "order not
        # open" are equivalent rejects); eviction recycles the handle and,
        # when the symbol goes quiet, the slot. Cost is O(batch + fills):
        # terminal makers were collected in decode pass 2 — never by
        # sweeping the whole directory of resting orders.
        for e in ops:
            i = e.info
            if e.op in (OP_SUBMIT, OP_REST) and i.status in (FILLED, CANCELED, REJECTED):
                self._evict(i)
            elif e.op == OP_CANCEL and i.status == CANCELED:
                self._evict(i)
        # Ascending handle order, NOT set-iteration order: recycling order
        # feeds the handle free list, and the native lane engine
        # (me_lanes.cpp finish) mirrors this exact sequence for bit-parity.
        for h in sorted(terminal_makers):
            info = self.orders_by_handle.get(h)
            if info is not None and info.status in (FILLED, CANCELED, REJECTED):
                self._evict(info)

    def _evict(self, info: OrderInfo) -> None:
        """Drop a terminal order from the directories; recycle its handle
        and (via the live count) possibly its symbol slot. Idempotent — an
        order can go terminal as taker and be collected as maker within the
        same dispatch."""
        if self.orders_by_handle.pop(info.handle, None) is None:
            return
        self.orders_by_id.pop(info.order_id, None)
        self._release_handle(info.handle)
        slot = self.symbols.get(info.symbol)
        if slot is not None:
            self._slot_release(slot)

    # -- decoding helpers --------------------------------------------------

    def _account(self, results, fills, overflow, by_handle,
                 res: DispatchResult, terminal_makers: set[int]) -> None:
        """The per-wave post-decode tail shared by every dispatch shape
        (sparse / dense / mesh): overflow metric, directory+event decode,
        fill accounting."""
        if overflow:
            self.metrics.inc("fill_buffer_overflows")
        self._decode_batch(results, fills, by_handle, res, terminal_makers)
        res.fill_count += len(fills)

    def _decode_batch(
        self, results, fills, by_handle, res: DispatchResult,
        terminal_makers: set[int],
    ) -> None:
        # Decode in DEVICE order: results arrive (symbol, batch-row)-sorted,
        # and each fill belongs to exactly one taker row, so applying a
        # taker's maker-consequences at its own row replays the scan's true
        # event order. This matters when one batch partially fills an order
        # and then cancels it: the fills happened before the cancel, so the
        # maker decrements must land before the cancel zeroes remaining
        # (processing them afterwards drove remaining negative — a CHECK
        # violation in the durable store). Grouping fills by taker up front
        # also makes the whole decode O(results + fills), not O(R*F).
        fills_by_taker: dict[int, list] = {}
        for f in fills:
            fills_by_taker.setdefault(f.taker_oid, []).append(f)

        for r in results:
            q = by_handle.get(r.oid)
            if not q:
                continue
            e = q.popleft()
            info = e.info
            if e.op in (OP_SUBMIT, OP_REST):
                info.status = r.status
                info.remaining = r.remaining
                if r.status == REJECTED:
                    # Book-capacity reject after any fills were honored:
                    # metered backpressure, never a silent drop — the
                    # positional reject reason below rides the batch
                    # statuses (record_flaws vocabulary) and the counter
                    # is the operator's re-tiering signal.
                    self._meter_capacity_reject(r.sym)
                    res.outcomes.append(
                        OpOutcome(e, r.status, r.filled, r.remaining,
                                  "book side at capacity" if r.filled == 0 else
                                  "partially filled; remainder rejected (book side at capacity)")
                    )
                else:
                    res.outcomes.append(OpOutcome(e, r.status, r.filled, r.remaining))
                price_col = (None if info.otype in (pb2.MARKET, MARKET_FOK)
                             else info.price_q4)
                res.storage_orders.append(
                    (info.order_id, info.client_id, info.symbol, info.side,
                     info.otype, price_col, info.quantity, info.remaining,
                     info.status)
                )
                self.orders_by_handle[info.handle] = info
                self.orders_by_id[info.order_id] = info
                # This row's executions: taker-side updates + maker
                # bookkeeping, in priority order. One storage row per
                # execution (order_id = aggressor, counter_order_id = maker);
                # the maker's remaining/status is an orders-table update.
                # Fill-record overflow leaves the taker's decoded fill list
                # short of its true executed quantity (r.filled comes from
                # the results lane, which never overflows). Ledger the gap:
                # the fills table will be missing exactly this much.
                decoded_fill_qty = sum(
                    f.quantity for f in fills_by_taker.get(info.handle, ())
                )
                if decoded_fill_qty < r.filled:
                    self._ledger_lost(info.order_id,
                                      r.filled - decoded_fill_qty)
                rem = info.quantity
                for f in fills_by_taker.get(info.handle, ()):
                    rem -= f.quantity
                    if self._build_ou:
                        st = (FILLED if (rem == 0 and info.remaining == 0)
                              else PARTIALLY_FILLED)
                        res.order_updates.append(
                            self._update(info, st, f.price_q4, f.quantity, rem)
                        )
                    maker = self.orders_by_handle.get(f.maker_oid)
                    if maker is None:
                        continue  # unreachable if directories are consistent
                    maker.remaining -= f.quantity
                    maker.status = FILLED if maker.remaining == 0 else PARTIALLY_FILLED
                    if maker.remaining == 0:
                        terminal_makers.add(f.maker_oid)
                    res.storage_fills.append(
                        FillRow(info.order_id, maker.order_id, f.price_q4, f.quantity)
                    )
                    res.storage_updates.append(
                        (maker.order_id, maker.status, maker.remaining)
                    )
                    if self._build_ou:
                        res.order_updates.append(
                            self._fill_update(maker, f.price_q4, f.quantity)
                        )
                if self._build_ou and r.status in (NEW, CANCELED, REJECTED):
                    res.order_updates.append(
                        self._update(info, r.status, 0, 0, r.remaining))
            elif e.op == OP_AMEND:
                if r.status == NEW:
                    # quantity and remaining shrink together by the same
                    # delta, so filled (= quantity - remaining) and the
                    # store's CHECK arithmetic are untouched.
                    filled_so_far = info.quantity - info.remaining
                    info.remaining = r.remaining
                    info.quantity = filled_so_far + r.remaining
                    res.outcomes.append(OpOutcome(e, NEW, 0, r.remaining))
                    # Amends ride the updates stream as 4-tuples (the
                    # extra field is the new quantity); both sinks split
                    # them onto the quantity-updating statement.
                    res.storage_updates.append(
                        (info.order_id, info.status, info.remaining,
                         info.quantity))
                    if self._build_ou:
                        res.order_updates.append(self._update(
                            info, info.status, 0, 0, r.remaining))
                else:
                    res.outcomes.append(OpOutcome(
                        e, REJECTED, 0, 0,
                        "amend rejected (must strictly reduce an open "
                        "order's quantity)"))
            else:  # cancel
                if r.status == CANCELED:
                    info.status = CANCELED
                    info.remaining = 0
                    res.outcomes.append(OpOutcome(e, CANCELED, 0, r.remaining))
                    res.storage_updates.append((info.order_id, CANCELED, 0))
                    if self._build_ou:
                        res.order_updates.append(
                            self._update(info, CANCELED, 0, 0, 0))
                else:
                    res.outcomes.append(
                        OpOutcome(e, REJECTED, 0, 0, "order not open")
                    )

    def tier_of_slot(self, slot: int) -> int:
        """Capacity-tier group index owning a symbol slot — 0 for the
        single implicit tier of an untiered runner; the tiered runner
        overrides (server/tiered_runner.py)."""
        return 0

    def _meter_capacity_reject(self, slot: int) -> None:
        """Count one full-book submit reject: the venue-wide counter plus
        the per-tier series the operator re-tiers by (prose-documented
        like the per-lane series; OPERATIONS.md). Registry name has no
        _total suffix — the exposition appends it (the operator-facing
        series is me_book_capacity_rejects_total)."""
        self.metrics.inc("book_capacity_rejects")
        self.metrics.inc(
            f"book_capacity_rejects_tier{self.tier_of_slot(slot)}")

    def _update(self, info: OrderInfo, status, fprice, fqty, remaining) -> pb2.OrderUpdate:
        return pb2.OrderUpdate(
            order_id=info.order_id,
            client_id=info.client_id,
            symbol=info.symbol,
            status=status,
            fill_price=fprice,
            scale=4,
            fill_quantity=fqty,
            remaining_quantity=remaining,
        )

    def _fill_update(self, maker: OrderInfo, price, qty) -> pb2.OrderUpdate:
        return self._update(maker, maker.status, price, qty, maker.remaining)

    def _market_data(self, out, touched_syms, res: DispatchResult) -> None:
        # Top-of-book arrays may be globally sharded (mesh mode): read the
        # process-local block only — every touched symbol is local, since
        # this host only dispatched ops for symbols it owns.
        from matching_engine_tpu.parallel import hostlocal

        if self._sharded is not None:
            bb, lo, _ = hostlocal.local_block(out.best_bid)
            bs = hostlocal.local_block(out.bid_size)[0]
            ba = hostlocal.local_block(out.best_ask)[0]
            asz = hostlocal.local_block(out.ask_size)[0]
        else:
            bb = np.asarray(out.best_bid)
            bs = np.asarray(out.bid_size)
            ba = np.asarray(out.best_ask)
            asz = np.asarray(out.ask_size)
            lo = 0
        for s in touched_syms:
            sym = self.slot_symbols[s]
            if sym is None or not (lo <= s < lo + bb.shape[0]):
                continue
            res.market_data.append(
                pb2.MarketDataUpdate(
                    symbol=sym,
                    best_bid=int(bb[s - lo]),
                    best_ask=int(ba[s - lo]),
                    scale=4,
                    bid_size=int(bs[s - lo]),
                    ask_size=int(asz[s - lo]),
                )
            )

    # -- durability reconciliation -----------------------------------------

    def _ledger_lost(self, order_id: str, qty: int) -> None:
        if len(self.pending_recon) >= self._recon_cap:
            self.metrics.inc("recon_ledger_dropped")
            return
        self.pending_recon.append((order_id, "fills_lost", qty))

    def reconcile_fill_overflow(self) -> list[tuple]:
        """Repair the host directory against the device book after fill-
        record overflow (kernel max_fills). Caller must hold the dispatch
        lock (quiesced engine).

        Takers self-report their true filled/remaining through the results
        lane, but MAKER decrements are decoded from fill records — when
        those overflow, host maker state (and therefore SQLite) runs ahead
        of reality. The device book is the truth: every open order is a
        resting lane, so join directory handles against the lanes and adopt
        the device remaining. Returns [(order_id, remaining, status,
        lost_qty)] repair rows for the durable store; matching
        ("fills_lost") entries are appended to pending_recon.
        """
        lanes = self._live_lane_qtys()
        repairs: list[tuple] = []
        for handle, info in list(self.orders_by_handle.items()):
            dev_rem = lanes.get(handle)
            if dev_rem is None:
                # Open on the host, gone from the book: fully consumed by
                # fills whose records overflowed (cancels/rejects always
                # surface through the results lane, so this is a fill).
                lost = info.remaining
                info.remaining = 0
                info.status = FILLED
                repairs.append((info.order_id, 0, FILLED, lost))
                self._ledger_lost(info.order_id, lost)
                self._evict(info)
            elif dev_rem != info.remaining:
                lost = info.remaining - dev_rem
                info.remaining = dev_rem
                info.status = PARTIALLY_FILLED
                repairs.append(
                    (info.order_id, dev_rem, PARTIALLY_FILLED, lost))
                self._ledger_lost(info.order_id, lost)
        return repairs

    def _live_lane_qtys(self) -> dict[int, int]:
        """handle -> device remaining for every live resting lane (the
        reconcile join source; the tiered runner unions its per-tier
        books)."""
        from matching_engine_tpu.parallel import hostlocal

        lanes: dict[int, int] = {}
        with self._snapshot_lock:
            # Local block only: this host's directory can only reference
            # handles resting in its own symbol rows.
            arrs = [
                hostlocal.local_block(x)[0]
                for x in (self.book.bid_oid, self.book.bid_qty,
                          self.book.ask_oid, self.book.ask_qty)
            ]
        for oid_arr, qty_arr in ((arrs[0], arrs[1]), (arrs[2], arrs[3])):
            mask = qty_arr > 0
            for h, q in zip(oid_arr[mask].tolist(), qty_arr[mask].tolist()):
                lanes[int(h)] = int(q)
        return lanes

    def drain_recon(self) -> list[tuple[str, str, int]]:
        """Take (and clear) the pending durability-gap ledger entries."""
        out = self.pending_recon
        self.pending_recon = []
        return out

    # -- read-only views ---------------------------------------------------

    def _owner_for(self, client_id: str) -> int:
        """Collision-free STP identity for a client (called under the
        dispatch lock). First sight assigns owner_hash when free, else
        linear-probes to the next unclaimed id (counted + logged), and
        queues the assignment for durable persistence."""
        if not client_id:
            return 0
        owner = self._owner_by_client.get(client_id)
        if owner is not None:
            return owner
        if len(self._owner_by_client) >= self._owner_registry_cap:
            # Bounded like the pre-registry watch map: past the cap (a
            # client-id churn attack / misconfigured id-per-order client)
            # new ids probe UNREGISTERED — the registry/db stop growing
            # and the id is not remembered, so two overflow clients with
            # the same hash can still merge (counted residual risk). But
            # the probe MUST still skip claimed ids: returning a raw hash
            # that a registered client was remapped AWAY from would merge
            # the overflow client with a client whose id doesn't even
            # hash-collide (ADVICE r4 low).
            self.metrics.inc("owner_registry_overflow")
            owner = owner_hash(client_id)
            while owner in self._owner_claimed or owner == 0:
                owner = (owner + 1) & 0x7FFFFFFF
            return owner
        owner = owner_hash(client_id)
        if owner in self._owner_claimed:
            self.metrics.inc("owner_hash_collisions")
            first = self._owner_claimed[owner]
            while owner in self._owner_claimed or owner == 0:
                owner = (owner + 1) & 0x7FFFFFFF
            print(f"[runner] owner_hash collision: {client_id!r} vs "
                  f"{first!r}; remapped to {owner}")
        self._owner_by_client[client_id] = owner
        self._owner_claimed[owner] = client_id
        self.pending_owner_ids.append((client_id, owner))
        self.metrics.inc("owner_ids_assigned")  # == registry size (gauge)
        return owner

    def load_owner_ids(self, rows: list[tuple[str, int]]) -> None:
        """Install persisted STP assignments (boot path, before any
        dispatch/replay derives identities)."""
        for client_id, owner in rows:
            self._owner_by_client[client_id] = owner
            self._owner_claimed[owner] = client_id

    def flush_owner_ids(self) -> None:
        """Drain pending first-sight assignments to the durable registry.
        A failed write stays queued and self-heals at the next flush
        point, like flush_auction_mode.

        Locking: normally called with no engine locks held (a SQLite
        busy-wait must stay off the dispatch critical path), with ONE
        deliberate exception — CheckpointDaemon.checkpoint_now calls this
        under the dispatch lock as part of the snapshot durability
        barrier (checkpointed book lanes freeze assigned owner ints, so
        the assignments must be durable first); that write is bounded by
        the storage layer's busy_timeout. Concurrent flush callers
        serialize on _owner_flush_lock; see its init comment for why
        producers don't need it."""
        if self.persist_owner_ids is None:
            return
        # The lock spans precheck + persist + requeue: a barrier caller
        # (checkpoint) that sees an empty pending list must be guaranteed
        # no OTHER flusher still has a drained-but-unpersisted batch in
        # flight — otherwise the snapshot could freeze owner ints that a
        # failed persist then re-queues, and a crash before the retry
        # restores diverged identities. The write inside is bounded by
        # the storage connection's busy timeout.
        with self._owner_flush_lock:
            if not self.pending_owner_ids:
                return
            batch = list(self.pending_owner_ids)
            del self.pending_owner_ids[:len(batch)]
            try:
                ok = self.persist_owner_ids(batch)
            except Exception as e:  # noqa: BLE001 — never unwind
                print(f"[runner] owner_ids persist raised: "
                      f"{type(e).__name__}: {e}")
                ok = False
            if ok is False:
                self.metrics.inc("meta_persist_failures")
                self.pending_owner_ids[:0] = batch

    def set_auction_mode(self, value: bool) -> None:
        """Flip the call-period flag and mark it dirty; the durable write
        happens in flush_auction_mode, OUTSIDE the dispatch lock — a
        SQLite busy-wait must never sit on the dispatch critical path.

        Every admissible EngineConfig can uncross (wide-sum formulation
        at sorted venue depth), but the guard stays: a config whose
        rested interest could never be uncrossed must not OPEN a call
        period, or the period could only be ended out-of-band."""
        from matching_engine_tpu.engine.book import auction_capacity_max

        if value and self.cfg.capacity > auction_capacity_max(
                self.cfg.kernel):
            raise ValueError(
                f"call periods unsupported at capacity "
                f"{self.cfg.capacity} (auction bound "
                f"{auction_capacity_max(self.cfg.kernel)})")
        self.auction_mode = value
        self._mode_dirty = True

    def flush_auction_mode(self) -> None:
        """Persist a dirty call-period flag (call with no engine locks
        held). A failed write is WARNED and counted — the next boot could
        otherwise resume the wrong trading mode (the crossed-book safety
        net only covers the stale-continuous direction).

        Concurrent flushers serialize on _owner_flush_lock (the sibling
        flush_owner_ids discipline); set_auction_mode stays LOCK-FREE —
        it may run under the dispatch lock, and a SQLite busy-wait must
        never sit on the dispatch critical path. Correctness instead
        rests on ordering: the dirty flag clears BEFORE the value is
        read, and set_auction_mode writes value-then-dirty — a flip
        landing mid-persist re-marks dirty after our clear, so the next
        flush re-persists it. The old persist-then-clear order could
        clear a concurrent flip it never wrote (lockset analyzer
        finding; pinned by test_flush_auction_mode_concurrent_flip)."""
        if not self._mode_dirty or self.persist_auction_mode is None:
            return
        with self._owner_flush_lock:
            if not self._mode_dirty:
                return
            self._mode_dirty = False
            value = self.auction_mode
            try:
                ok = self.persist_auction_mode(value)
            except Exception as e:  # noqa: BLE001 — never unwind
                print(f"[runner] auction_mode persist raised: "
                      f"{type(e).__name__}: {e}")
                ok = False
            if ok is False:
                # Stay dirty: the write self-heals at the next flush
                # point (e.g. the next RunAuction) instead of depending
                # on an operator noticing the warning.
                self._mode_dirty = True
                self.metrics.inc("meta_persist_failures")
                print(f"[runner] WARNING: failed to persist "
                      f"auction_mode={value}; a restart may resume "
                      f"the wrong trading mode")

    def maybe_rebase_seqs(self) -> bool:
        """Renumber book seqs when any book's arrival counter nears the
        int32 cliff (engine/maintenance.py). Call at a QUIESCE point:
        under the dispatch lock with no staged dispatches (the
        checkpoint daemon's barrier is the intended site). Rare by
        construction — 2^30 arrivals on one symbol between checks."""
        from matching_engine_tpu.engine.maintenance import (
            REBASE_THRESHOLD,
            rebase_seqs,
        )

        if self._sharded is not None and jax.process_count() > 1:
            # checkpoint_now is collective-free by design (each host
            # saves its addressable shards on its own schedule); an
            # ad-hoc global reduction or a one-host jitted rebase here
            # would deadlock the mesh. Multi-host deployments rebase via
            # restart instead: recovery replay re-rests open orders with
            # fresh seqs 0..n (the same renumbering, for free).
            self.metrics.inc("seq_rebase_skipped_multihost")
            return False
        mx = int(np.max(np.asarray(self.book.next_seq)))
        if mx < REBASE_THRESHOLD:
            return False
        with self._snapshot_lock:
            # Donated input: assign under the snapshot lock like every
            # other book-replacing step.
            self.book = rebase_seqs(self.cfg, self.book)
        self.metrics.inc("seq_rebases")
        print(f"[runner] seq rebase at next_seq={mx} (threshold "
              f"{REBASE_THRESHOLD}): priority order preserved, counters "
              f"reset to live counts")
        return True

    def crossed_symbols(self) -> list[str]:
        """Symbols (this host's) whose books stand CROSSED (best bid >=
        best ask). A continuously-matched book can never stand crossed, so
        a crossed book after recovery means the durable state was written
        during an auction call period — the caller must resume it
        (auction_mode) rather than expose the book to continuous matching.
        Reads addressable shards only (multi-process safe)."""
        out = []
        for lo, crossed in self._crossed_blocks():
            for i in np.nonzero(crossed)[0]:
                sym = self.slot_symbols[lo + int(i)]
                if sym is not None:
                    out.append(sym)
        return out

    def _crossed_blocks(self):
        """[(block_lo, crossed_mask)] over this runner's book(s) — one
        block here, one per tier in the tiered runner."""
        from matching_engine_tpu.parallel import hostlocal

        with self._snapshot_lock:
            bp, lo, _ = hostlocal.local_block(self.book.bid_price)
            bq = hostlocal.local_block(self.book.bid_qty)[0]
            ap = hostlocal.local_block(self.book.ask_price)[0]
            aq = hostlocal.local_block(self.book.ask_qty)[0]
        imin, imax = np.iinfo(np.int32).min, np.iinfo(np.int32).max
        best_bid = np.where(bq > 0, bp, imin).max(axis=1)
        best_ask = np.where(aq > 0, ap, imax).min(axis=1)
        crossed = ((bq > 0).any(axis=1) & (aq > 0).any(axis=1)
                   & (best_bid >= best_ask))
        return [(lo, crossed)]

    def _snapshot_row(self, slot: int):
        """One symbol's 8 book-lane rows (bid p/q/oid/seq, ask p/q/oid/
        seq) as host arrays — the snapshot source both runner flavors'
        joins read; the tiered runner serves it from the owning tier's
        book."""
        with self._snapshot_lock:
            # read_row touches only the shard holding this symbol's lanes —
            # valid on a multi-process mesh, where a whole-array read isn't.
            from matching_engine_tpu.parallel import hostlocal

            return [
                hostlocal.read_row(x, slot)
                for x in (
                    self.book.bid_price, self.book.bid_qty, self.book.bid_oid,
                    self.book.bid_seq, self.book.ask_price, self.book.ask_qty,
                    self.book.ask_oid, self.book.ask_seq,
                )
            ]

    def book_snapshot(self, symbol: str) -> tuple[list, list]:
        """Priority-sorted (OrderInfo, qty) lists (bids, asks) for one symbol.

        Fetches the one symbol's lanes from the device (tiny transfer) and
        joins against the host order directory.
        """
        slot = self.symbols.get(symbol)
        if slot is None:
            return [], []
        bp, bq, bo, bs_, ap, aq, ao, as_ = self._snapshot_row(slot)

        def side(price, qty, oid, seq, desc, want_side):
            rows = [
                (int(oid[j]), int(price[j]), int(qty[j]), int(seq[j]))
                for j in np.nonzero(qty > 0)[0]
            ]
            rows.sort(key=lambda r: (-r[1] if desc else r[1], r[3]))
            out = []
            for o, p, q, _ in rows:
                info = self.orders_by_handle.get(o)
                # The join runs without the dispatch lock, so a lane's handle
                # can go terminal and be reassigned to an unrelated order
                # between the lane copy and this lookup. A recycled handle
                # can't collide on (symbol, side, price) with the lane it
                # vacated unless it is a legitimately equivalent resting
                # order, so a consistency guard keeps stale joins out.
                if (
                    info is not None
                    and info.symbol == symbol
                    and info.side == want_side
                    and info.price_q4 == p
                ):
                    out.append((info, q))
            return out

        return (
            side(bp, bq, bo, bs_, True, BUY),
            side(ap, aq, ao, as_, False, SELL),
        )
