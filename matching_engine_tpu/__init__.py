"""matching_engine_tpu — a TPU-native order-matching framework.

Capability surface of julien-mrty/Matching_Engine (gRPC order gateway, Q4
scaled-integer prices, SQLite orders/fills persistence) with the matching
core the reference declared but never implemented, built TPU-first:
fixed-shape struct-of-arrays books, a jit/vmap'd price-time-priority match
kernel, symbol-sharded shard_map scaling over a device mesh, and host shells
(gRPC front end, batch dispatcher, async storage sink) around the device
pipeline. See SURVEY.md at the repo root for the full blueprint.
"""

__version__ = "0.1.0"
