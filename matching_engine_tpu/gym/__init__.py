"""Many-venue market gym: V independent venues in one jit'd scan.

ROADMAP Open item 5 ("Simulation as a product"). See gym/env.py for the
step/reset environment and gym/episode.py for freezing an episode into a
replayable workload artifact.
"""

from matching_engine_tpu.gym.episode import episode_roles, freeze_episode
from matching_engine_tpu.gym.env import (
    GymObs,
    GymSpec,
    GymState,
    GymStepStats,
    VenueControls,
    VenueGym,
    build_controls,
    restore_state,
    save_state,
)

__all__ = [
    "GymObs",
    "GymSpec",
    "GymState",
    "GymStepStats",
    "VenueControls",
    "VenueGym",
    "build_controls",
    "episode_roles",
    "freeze_episode",
    "restore_state",
    "save_state",
]
