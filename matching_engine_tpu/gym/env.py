"""Vmapped many-venue simulation gym: step/reset over [V] markets.

JAX-LOB (arXiv:2308.13289) demonstrated that thousands of *parallel*
limit-order-book environments on one accelerator are what unlock
RL-scale trading research. This module lifts the venue axis over the
whole sim stack: V independent venues — each a full [S, CAP] book batch
with its own heterogeneous agent population — step together inside ONE
jit'd program (and `rollout` runs T such steps in one lax.scan), behind
a gym-style step/reset API.

Heterogeneity across the V axis (all traced, one compiled program):

- **seeds**: per-venue PRNG bases. Venue v's stream is
  `fold_in(PRNGKey(seed_v + episode), symbol)` — exactly the
  single-venue scenario derivation at episode 0, so a V-venue rollout is
  bit-identical to V independent `run_scenario` runs (the parity oracle,
  tests/test_gym.py), and changing venue w's seed can never perturb
  venue v (PRNG independence, pinned).
- **phase programs**: each venue runs its own Scenario. Phase kinds /
  burst windows / shock schedules compile to [V, T] control tables
  (build_controls) indexed by each venue's own episode step, so venues
  in different phases coexist in one step: one venue holds a call
  auction while another is halted and a third trades continuously.
- **Zipf mixes**: per-venue hot-symbol skew ([V, S] activity weights).
- **class gates**: per-venue agent fire-probability overrides
  (sim/agents.ClassGates) — venues can run noisier or more aggressive
  populations than their neighbours without recompiling.

Episode lifecycle: a venue's episode is its scenario program, length
`ep_len[v]` steps. When a venue's episode ends it AUTO-RESETS in the
same step (fresh book, fresh agent state, next episode's seed =
`seed_v + episode`) — the returned observation is already the reset
venue's; `done[v]` marks the boundary. Episode boundaries are pure step
arithmetic: NO wall clock enters the state, the artifacts, or the
checkpoints (save_state/restore_state write step-indexed state only via
the checkpoint machinery's atomic writer), so a restored run continues
bit-identically — the determinism analyzer scans this module and there
is nothing to waive.

Observations are TOB/depth slices per venue ([V, S] best bid/ask,
sizes, resting depth per side); actions are oprec-style order lanes
`[V, S, action_slots, 7]` (book.batch_from_lanes columns) injected
alongside the agent flow each step — they ride the same engine
dispatch, the same call-period OP_REST mapping and the same halt gating
as agent orders. Any interesting episode freezes into a replayable
opfile + manifest via gym/episode.py.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from matching_engine_tpu.engine.book import (
    BookBatch,
    EngineConfig,
    batch_from_lanes,
)
from matching_engine_tpu.engine.kernel import (
    LIMIT,
    OP_REST,
    OP_SUBMIT,
    apply_halt_mask,
)
from matching_engine_tpu.engine.venues import (
    venue_step_core,
    venue_top_of_book,
    venue_uncross,
)
from matching_engine_tpu.sim.agents import (
    AgentMix,
    AgentState,
    ClassGates,
    agent_orders,
    init_agents,
    observe_market,
)
from matching_engine_tpu.sim.scenarios import Scenario, zipf_weights_q15

I32 = jnp.int32

# Recommended base for caller-assigned action-lane order ids: far above
# any oid the agent populations can reach in an episode (next_oid grows
# by the batch width per active step), so injected orders never collide
# with agent orders in the per-symbol id space. The episode freezer
# renumbers both through one map, so this is a convention, not a
# correctness requirement.
ACTION_OID_BASE = 1 << 28


@dataclasses.dataclass(frozen=True)
class GymSpec:
    """Static gym configuration (hashable; jit-static).

    cfg is the PER-VENUE engine config ([S, CAP] books; untiered — the
    venue axis is the scaling dimension here); mix is the shared batch
    LAYOUT (lane counts are shape-static; per-venue behaviour varies
    through traced controls, not through the layout)."""

    cfg: EngineConfig
    mix: AgentMix
    venues: int
    action_slots: int = 0
    # Static auction switch: when NO venue's program contains a call
    # phase the compiled step omits the uncross branch entirely.
    has_auction: bool = False
    # Venues whose per-step order lanes the step/rollout additionally
    # returns (the episode freezer's capture hook). Keep this small —
    # each recorded venue stacks [T, S, B + action_slots, 7] on host.
    record: tuple[int, ...] = ()

    def __post_init__(self):
        assert self.venues >= 1
        assert self.cfg.batch == self.mix.batch_for(), (
            f"EngineConfig.batch must be {self.mix.batch_for()} "
            f"for this AgentMix")
        assert not self.cfg.tiers, "gym venues are untiered"
        assert all(0 <= v < self.venues for v in self.record)

    def lanes(self) -> int:
        """Engine batch width per symbol: agent lanes + action slots."""
        return self.mix.batch_for() + self.action_slots

    def engine_cfg(self) -> EngineConfig:
        """The per-venue engine config the kernels actually step (batch
        widened by the action slots)."""
        if self.action_slots == 0:
            return self.cfg
        return dataclasses.replace(self.cfg, batch=self.lanes())


class VenueControls(NamedTuple):
    """Per-venue episode programs as device tables ([V, T] indexed by
    each venue's own episode step; T = max episode length). Built once
    per env (build_controls) from per-venue Scenarios — deterministic
    numpy, part of the gym's reproducible identity."""

    call: jax.Array       # [V, T] bool — call period (auction phase)
    halt: jax.Array       # [V, T] bool — trading halt
    burst_on: jax.Array   # [V, T] bool — burst-window arrival gate
    shock: jax.Array      # [V, T] i32 — per-step fair-value decrement
    sell_bias: jax.Array  # [V, T] bool — shock window (takers all SELL)
    uncross: jax.Array    # [V, T] bool — call phase closes after step t
    ep_len: jax.Array     # [V] i32 episode length (scenario total)
    zipf_w: jax.Array     # [V, S] i32 Q15 per-symbol activity weights
    noise_p: jax.Array    # [V] i32 per-venue class-gate overrides
    mom_p: jax.Array      # [V] i32
    taker_p: jax.Array    # [V] i32


class GymState(NamedTuple):
    """Device-resident state of all V venues."""

    books: BookBatch      # fields [V, S, CAP] ([V, S] next_seq)
    agents: AgentState    # fields [V, ...]
    ep_step: jax.Array    # [V] i32 step within the current episode
    episode: jax.Array    # [V] i32 episode counter
    seed: jax.Array       # [V] i32 per-venue base seed


class GymObs(NamedTuple):
    """Per-venue market observation (all [V, S] unless noted)."""

    best_bid: jax.Array
    bid_size: jax.Array
    best_ask: jax.Array
    ask_size: jax.Array
    depth_bid: jax.Array  # resting order count, bid side
    depth_ask: jax.Array  # resting order count, ask side
    ep_step: jax.Array    # [V]
    episode: jax.Array    # [V]
    done: jax.Array       # [V] bool — episode ended (and auto-reset)


class GymStepStats(NamedTuple):
    """Per-venue step ground truth (all [V]). Continuous fills and
    call-auction executions are reported separately; auction volume
    comes back as base-2^15 limbs like the engine's AuctionOutput
    (recombine `(hi << 15) + lo` at int64 on host)."""

    real_ops: jax.Array
    fills: jax.Array
    volume: jax.Array
    uncrossed: jax.Array     # bool — this step closed a call phase
    uncross_hi: jax.Array
    uncross_lo: jax.Array
    uncross_aborted: jax.Array
    done: jax.Array


def build_controls(spec: GymSpec, scenarios, *, gates=None,
                   zipf_alpha_q8=None) -> VenueControls:
    """Compile per-venue Scenario programs into device control tables.

    `scenarios` is one Scenario per venue (a shorter list is cycled —
    the cheap way to spread a catalogue across many venues). Optional
    per-venue overrides: `gates` (list of ClassGates or None entries)
    and `zipf_alpha_q8` (list of ints; None entries fall back to the
    venue scenario's own zipf_alpha_q8). The table semantics replicate
    scenarios._phase_impl exactly — same burst/shock window arithmetic,
    same call/halt flags — so a venue's trajectory is bit-identical to
    run_scenario on its program."""
    v, s = spec.venues, spec.cfg.num_symbols
    progs = [scenarios[i % len(scenarios)] for i in range(v)]
    assert all(isinstance(p, Scenario) for p in progs)
    t_max = max(p.total_steps() for p in progs)

    call = np.zeros((v, t_max), dtype=bool)
    halt = np.zeros((v, t_max), dtype=bool)
    burst = np.ones((v, t_max), dtype=bool)
    shock = np.zeros((v, t_max), dtype=np.int32)
    bias = np.zeros((v, t_max), dtype=bool)
    uncx = np.zeros((v, t_max), dtype=bool)
    ep_len = np.zeros((v,), dtype=np.int32)
    zipf = np.zeros((v, s), dtype=np.int32)

    for i, prog in enumerate(progs):
        start = 0
        for ph in prog.phases:
            end = start + ph.steps
            if ph.kind == "auction":
                call[i, start:end] = True
                uncx[i, end - 1] = True
            elif ph.kind == "halt":
                halt[i, start:end] = True
            t = np.arange(ph.steps)
            if ph.burst_period:
                burst[i, start:end] = (t % ph.burst_period) < ph.burst_on
            if ph.shock_len:
                in_shock = (t >= ph.shock_start) & (
                    t < ph.shock_start + ph.shock_len)
                shock[i, start:end] = np.where(in_shock, ph.shock_bp, 0)
                bias[i, start:end] = in_shock
            start = end
        ep_len[i] = start
        alpha = prog.zipf_alpha_q8
        if zipf_alpha_q8 is not None and zipf_alpha_q8[i] is not None:
            alpha = zipf_alpha_q8[i]
        zipf[i] = zipf_weights_q15(s, alpha)

    if spec.has_auction != bool(uncx.any()):
        raise ValueError(
            f"GymSpec.has_auction={spec.has_auction} but the venue "
            f"programs {'do' if uncx.any() else 'do not'} contain call "
            f"phases — the static switch must match the programs")

    mix = spec.mix
    g_nz = np.full((v,), mix.noise_p, dtype=np.int32)
    g_mo = np.full((v,), mix.mom_p, dtype=np.int32)
    g_tk = np.full((v,), mix.taker_p, dtype=np.int32)
    if gates is not None:
        for i, g in enumerate(gates):
            if g is not None:
                g_nz[i], g_mo[i], g_tk[i] = g.noise_p, g.mom_p, g.taker_p

    return VenueControls(
        call=jnp.asarray(call), halt=jnp.asarray(halt),
        burst_on=jnp.asarray(burst), shock=jnp.asarray(shock),
        sell_bias=jnp.asarray(bias), uncross=jnp.asarray(uncx),
        ep_len=jnp.asarray(ep_len), zipf_w=jnp.asarray(zipf),
        noise_p=jnp.asarray(g_nz), mom_p=jnp.asarray(g_mo),
        taker_p=jnp.asarray(g_tk),
    )


def _init_books(spec: GymSpec) -> BookBatch:
    v, s, c = spec.venues, spec.cfg.num_symbols, spec.cfg.capacity

    # Distinct buffers per field (engine/book.py init_book rule).
    def z():
        return jnp.zeros((v, s, c), dtype=I32)

    return BookBatch(
        bid_price=z(), bid_qty=z(), bid_oid=z(), bid_seq=z(), bid_owner=z(),
        ask_price=z(), ask_qty=z(), ask_oid=z(), ask_seq=z(), ask_owner=z(),
        next_seq=jnp.zeros((v, s), dtype=I32),
    )


def _reset_impl(spec: GymSpec, seeds: jax.Array) -> GymState:
    agents = jax.vmap(
        lambda sd: init_agents(spec.cfg, spec.mix, sd))(seeds)
    v = spec.venues
    return GymState(
        books=_init_books(spec),
        agents=agents,
        ep_step=jnp.zeros((v,), I32),
        episode=jnp.zeros((v,), I32),
        seed=seeds.astype(I32),
    )


def _obs_of(spec: GymSpec, state: GymState, done) -> GymObs:
    bb, bs, ba, az = venue_top_of_book(state.books)
    return GymObs(
        best_bid=bb, bid_size=bs, best_ask=ba, ask_size=az,
        depth_bid=jnp.sum(state.books.bid_qty > 0, axis=2).astype(I32),
        depth_ask=jnp.sum(state.books.ask_qty > 0, axis=2).astype(I32),
        ep_step=state.ep_step, episode=state.episode, done=done,
    )


def _step_impl(spec: GymSpec, state: GymState, controls: VenueControls,
               actions: jax.Array):
    """One gym step for all venues. Returns (state, obs, stats, rec)
    where rec is the recorded venues' consumed order lanes
    [R, S, lanes, 7] (R = len(spec.record); zero-size when none)."""
    cfg, mix, v = spec.cfg, spec.mix, spec.venues
    s = cfg.num_symbols
    t = state.ep_step

    def at_t(tab):
        return jnp.take_along_axis(tab, t[:, None], axis=1)[:, 0]

    call = at_t(controls.call)
    halt = at_t(controls.halt)
    burst = at_t(controls.burst_on)
    shock = at_t(controls.shock)
    bias = at_t(controls.sell_bias)
    gates = ClassGates(noise_p=controls.noise_p, mom_p=controls.mom_p,
                       taker_p=controls.taker_p)

    def one_venue(astate, zw, c_, h_, b_, sh_, sb_, g):
        return agent_orders(cfg, mix, astate, zw, call_mode=c_, halt=h_,
                            burst_on=b_, shock=sh_, sell_bias=sb_, gates=g)

    agents, orders = jax.vmap(one_venue)(
        state.agents, controls.zipf_w, call, halt, burst, shock, bias,
        gates)

    if spec.action_slots:
        act = batch_from_lanes(actions)
        # Injected flow obeys the same venue state machinery as agent
        # flow: nothing is admitted during a halt.
        act = apply_halt_mask(
            act, jnp.broadcast_to(halt[:, None], (v, s)))
        orders = jax.tree_util.tree_map(
            lambda a, b: jnp.concatenate([a, b], axis=2), orders, act)

    # Call period: LIMIT submits rest without matching — the serving
    # stack's auction-mode mapping, applied to agent AND action flow.
    orders = orders._replace(op=jnp.where(
        call[:, None, None] & (orders.op == OP_SUBMIT)
        & (orders.otype == LIMIT), OP_REST, orders.op))

    books, raw = venue_step_core(spec.engine_cfg(), state.books, orders)
    _status, _filled, _remaining, _f_oid, f_qty, _f_price = raw

    # Close the momentum loop on the post-match TOB (the single-venue
    # scan observes BEFORE any phase-end uncross; same here).
    bb, _, ba, _ = venue_top_of_book(books)
    agents = jax.vmap(
        lambda st, b1, a1: observe_market(mix, st, b1, a1))(agents, bb, ba)

    fills = jnp.sum(f_qty > 0, axis=(1, 2, 3)).astype(I32)
    volume = jnp.sum(f_qty, axis=(1, 2, 3)).astype(I32)
    real_ops = jnp.sum(orders.op != 0, axis=(1, 2)).astype(I32)

    if spec.has_auction:
        uncx = at_t(controls.uncross)
        mask = jnp.broadcast_to(uncx[:, None], (v, s))

        def do_uncross(bks):
            return venue_uncross(spec.engine_cfg(), bks, mask)

        def no_uncross(bks):
            zvs = jnp.zeros((v, s), I32)
            return (bks, zvs, zvs, zvs, jnp.zeros((v,), bool))

        books, _p_star, ex_hi, ex_lo, aborted = jax.lax.cond(
            jnp.any(uncx), do_uncross, no_uncross, books)
        un_hi = jnp.sum(ex_hi, axis=1).astype(I32)
        un_lo = jnp.sum(ex_lo, axis=1).astype(I32)
    else:
        uncx = jnp.zeros((v,), bool)
        un_hi = un_lo = jnp.zeros((v,), I32)
        aborted = jnp.zeros((v,), bool)

    # Episode boundary: pure step arithmetic, auto-reset in-step. The
    # next episode reseeds at base_seed + episode — deterministic, no
    # wall clock anywhere near the boundary.
    t2 = t + 1
    done = t2 >= controls.ep_len
    episode = state.episode + done.astype(I32)
    reseed = state.seed + episode

    def with_reset(operand):
        agents_c, books_c = operand
        fresh = jax.vmap(
            lambda sd: init_agents(cfg, mix, sd))(reseed)

        def sel(f, c):
            m = done.reshape((v,) + (1,) * (f.ndim - 1))
            return jnp.where(m, f, c)

        agents_r = jax.tree_util.tree_map(sel, fresh, agents_c)
        books_r = jax.tree_util.tree_map(
            lambda c: sel(jnp.zeros_like(c), c), books_c)
        return agents_r, books_r

    agents, books = jax.lax.cond(
        jnp.any(done), with_reset, lambda op: op, (agents, books))

    new_state = GymState(
        books=books, agents=agents,
        ep_step=jnp.where(done, 0, t2),
        episode=episode, seed=state.seed,
    )
    obs = _obs_of(spec, new_state, done)
    stats = GymStepStats(
        real_ops=real_ops, fills=fills, volume=volume,
        uncrossed=uncx, uncross_hi=un_hi, uncross_lo=un_lo,
        uncross_aborted=aborted, done=done,
    )
    rec_idx = jnp.asarray(spec.record, dtype=I32).reshape((-1,))
    lanes = jnp.stack(
        [orders.op, orders.side, orders.otype, orders.price, orders.qty,
         orders.oid, orders.owner], axis=-1)[rec_idx]
    return new_state, obs, stats, lanes


def _rollout_impl(spec: GymSpec, steps: int, state: GymState,
                  controls: VenueControls, actions: jax.Array):
    """T gym steps in ONE lax.scan — the many-venue throughput path.
    `actions` is [T, V, S, A, 7] (A may be 0). Returns (state, stats
    stacked [T, V], recorded lanes [T, R, S, lanes, 7], final obs)."""

    def body(carry, act_t):
        st, obs_t, stats_t, rec_t = _step_impl(spec, carry, controls,
                                               act_t)
        return st, (stats_t, rec_t)

    state, (stats, rec) = jax.lax.scan(body, state, actions,
                                       length=steps)
    done_last = state.ep_step == 0
    return state, stats, rec, _obs_of(spec, state, done_last)


_reset_jit = jax.jit(_reset_impl, static_argnums=0)
_step_jit = jax.jit(_step_impl, static_argnums=0)
_rollout_jit = jax.jit(_rollout_impl, static_argnums=(0, 1))


class VenueGym:
    """The step/reset product surface over _step_impl/_rollout_impl.

    Functional state (gym-in-JAX convention, JAX-LOB/gymnax style): the
    env object holds only the STATIC spec and the device control
    tables; every transition takes and returns an explicit GymState, so
    callers can fork, replay, or checkpoint any state at will.
    """

    def __init__(self, spec: GymSpec, controls: VenueControls):
        self.spec = spec
        self.controls = controls

    @classmethod
    def from_scenarios(cls, cfg: EngineConfig, mix: AgentMix, venues: int,
                       scenarios, *, action_slots: int = 0,
                       record: tuple[int, ...] = (), gates=None,
                       zipf_alpha_q8=None) -> "VenueGym":
        progs = [scenarios[i % len(scenarios)] for i in range(venues)]
        has_auction = any(
            ph.kind == "auction" for p in progs for ph in p.phases)
        spec = GymSpec(cfg=cfg, mix=mix, venues=venues,
                       action_slots=action_slots, has_auction=has_auction,
                       record=tuple(record))
        return cls(spec, build_controls(spec, progs, gates=gates,
                                        zipf_alpha_q8=zipf_alpha_q8))

    def reset(self, seeds) -> tuple[GymState, GymObs]:
        """Fresh episode 0 for every venue. `seeds` is the [V] per-venue
        base seed vector (venue v, episode e draws from PRNGKey(
        seeds[v] + e))."""
        seeds = jnp.asarray(seeds, dtype=I32)
        assert seeds.shape == (self.spec.venues,), seeds.shape
        state = _reset_jit(self.spec, seeds)
        return state, _obs_of(self.spec, state,
                              jnp.zeros((self.spec.venues,), bool))

    def empty_actions(self, steps: int | None = None) -> jax.Array:
        """All-noop action lanes: [V, S, A, 7], or [T, V, S, A, 7] when
        `steps` is given (the rollout shape). A == spec.action_slots
        (possibly 0 — the zero-size array is a valid 'no actions')."""
        sp = self.spec
        shape = (sp.venues, sp.cfg.num_symbols, sp.action_slots, 7)
        if steps is not None:
            shape = (steps,) + shape
        return jnp.zeros(shape, dtype=I32)

    def step(self, state: GymState, actions=None):
        """(state, obs, stats, recorded_lanes)."""
        if actions is None:
            actions = self.empty_actions()
        return _step_jit(self.spec, state, self.controls, actions)

    def rollout(self, state: GymState, steps: int, actions=None,
                metrics=None):
        """T steps in one jit'd scan -> (state, stats [T, V], recorded
        lanes [T, R, S, lanes, 7], final obs)."""
        if actions is None:
            actions = self.empty_actions(steps)
        state, stats, rec, obs = _rollout_jit(
            self.spec, steps, state, self.controls, actions)
        if metrics is not None:
            sp = self.spec
            metrics.set_gauge("gym_venues", sp.venues)
            metrics.inc("gym_steps", steps)
            metrics.inc("gym_venue_steps", steps * sp.venues)
            metrics.inc("gym_fills", int(jnp.sum(stats.fills)))
            metrics.inc("gym_resets", int(jnp.sum(stats.done)))
        return state, stats, rec, obs


def gym_meta(spec: GymSpec) -> dict:
    """The checkpoint identity of a gym spec (JSON-shaped). Restore
    compatibility compares the engine semantic key + the population
    layout + the venue/action shape — the gym analogue of
    EngineConfig.semantic_key."""
    return {
        "cfg": dataclasses.asdict(spec.cfg),
        "mix": dataclasses.asdict(spec.mix),
        "venues": spec.venues,
        "action_slots": spec.action_slots,
    }


def save_state(spec: GymSpec, state: GymState, path: str) -> None:
    """Atomically checkpoint a gym state (tmp dir + rename — the
    checkpoint machinery's one atomic-swap implementation). The blocks
    are the raw [V]-axis arrays; the meta carries the gym identity and
    NO wall clock — a gym checkpoint is a pure function of (spec, state)
    and restoring it continues bit-identically (tests/test_gym.py pins
    it across the [V] axis on matrix and levels books)."""
    from matching_engine_tpu.utils.checkpoint import (
        _atomic_checkpoint_write,
    )

    blocks = {f"book_{f}": np.asarray(getattr(state.books, f))
              for f in BookBatch._fields}
    blocks.update({f"agent_{f}": np.asarray(getattr(state.agents, f))
                   for f in AgentState._fields})
    blocks.update({
        "ep_step": np.asarray(state.ep_step),
        "episode": np.asarray(state.episode),
        "seed": np.asarray(state.seed),
    })
    meta = {"format": 1, "kind": "gym", **gym_meta(spec)}
    _atomic_checkpoint_write(path, blocks, meta)


def restore_state(spec: GymSpec, path: str) -> GymState:
    """Load a gym checkpoint written by save_state, refusing on any
    semantic mismatch (different engine semantics, population layout,
    venue count, or action width)."""
    import json
    import os

    from matching_engine_tpu.utils.checkpoint import _cfg_from_meta

    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    if meta.get("kind") != "gym":
        raise ValueError(f"{path}: not a gym checkpoint")
    ck_cfg = _cfg_from_meta(meta)
    if ck_cfg.semantic_key() != spec.cfg.semantic_key():
        raise ValueError(
            f"{path}: engine semantics {ck_cfg.semantic_key()} != "
            f"{spec.cfg.semantic_key()}")
    known = {f.name for f in dataclasses.fields(AgentMix)}
    ck_mix = AgentMix(**{k: v for k, v in meta["mix"].items()
                         if k in known})
    if ck_mix != spec.mix:
        raise ValueError(f"{path}: agent mix differs from the spec")
    if (meta["venues"], meta["action_slots"]) != (spec.venues,
                                                  spec.action_slots):
        raise ValueError(
            f"{path}: venue/action shape {meta['venues']}/"
            f"{meta['action_slots']} != {spec.venues}/"
            f"{spec.action_slots}")
    with np.load(os.path.join(path, "book.npz")) as z:
        books = BookBatch(**{f: jnp.asarray(z[f"book_{f}"])
                             for f in BookBatch._fields})
        agents = AgentState(**{f: jnp.asarray(z[f"agent_{f}"])
                               for f in AgentState._fields})
        return GymState(
            books=books, agents=agents,
            ep_step=jnp.asarray(z["ep_step"]),
            episode=jnp.asarray(z["episode"]),
            seed=jnp.asarray(z["seed"]),
        )
