"""Freeze a gym episode into a replayable workload artifact.

Any interesting episode a venue runs — agent flow plus whatever actions
the caller injected — freezes into the SAME artifact pair the scenario
recorder writes (oprec opfile + JSON manifest, sim/record.py): the
serving stack replays it bit-faithfully with exact fill reconciliation,
`runner_bench --workload` drives it, and CI archives it. The decode is
sim/record.OpfileBuilder — one OID-renumbering rule, one client-identity
rule, one manifest schema for scenario recordings and gym episodes
alike (injected action lanes record under the "act" class tag).

The capture side is gym/env.py's `record` spec: recorded venues'
consumed order lanes come back from step/rollout as [T, R, S, B, 7]
arrays — the exact ops the engine matched, call-period OP_REST mapping
and halt gating included — so the freezer never re-simulates and an
episode with injected actions freezes exactly as it played.

No wall clock enters the artifact: every manifest field is a pure
function of (spec, scenario, seed, actions), so a frozen episode is as
reproducible as the scenario recordings beside it.
"""

from __future__ import annotations

import json

import numpy as np

from matching_engine_tpu.gym.env import GymSpec
from matching_engine_tpu.sim.agents import column_roles
from matching_engine_tpu.sim.record import (
    ACTION_CLASS,
    MANIFEST_FORMAT,
    OpfileBuilder,
    manifest_path_for,
)
from matching_engine_tpu.sim.scenarios import Scenario


def episode_roles(spec: GymSpec) -> list[tuple[int, str, int]]:
    """Batch-column roles of a gym dispatch: the agent mix's static
    layout, then one "act" column per action slot."""
    roles = column_roles(spec.mix)
    roles += [(ACTION_CLASS, "flow", a)
              for a in range(spec.action_slots)]
    return roles


def freeze_episode(
    spec: GymSpec,
    scenario: Scenario,
    venue: int,
    rec_lanes,
    stats,
    out_path: str,
    *,
    seed: int,
    episode: int = 0,
    serve_shards: int = 1,
    metrics=None,
    symbol_prefix: str = "S",
) -> dict:
    """Write one venue's episode as an opfile + manifest; returns the
    manifest dict (the scenario-recording schema plus source/venue/
    episode provenance).

    `rec_lanes`/`stats` are a rollout's captured outputs ([T, R, S, B,
    7] and GymStepStats over [T, V]); the rollout must START at the
    episode's first step (reset or a `done` boundary) and cover it
    fully. `venue` must be one of spec.record. `seed` is the venue's
    base seed and `episode` its episode counter at capture — together
    the artifact's reproducible identity (episode e draws from
    PRNGKey(seed + e))."""
    if venue not in spec.record:
        raise ValueError(f"venue {venue} is not recorded ({spec.record})")
    r = spec.record.index(venue)
    ep_len = scenario.total_steps()
    lanes = np.asarray(rec_lanes)[:, r]
    if lanes.shape[0] < ep_len:
        raise ValueError(
            f"rollout captured {lanes.shape[0]} steps < episode length "
            f"{ep_len}")
    done = np.asarray(stats.done)[:ep_len, venue]
    if not done[-1] or done[:-1].any():
        raise ValueError(
            "capture is not aligned to an episode: the rollout must "
            "start at the venue's episode start (reset/done boundary)")
    if np.asarray(stats.uncross_aborted)[:ep_len, venue].any():
        raise RuntimeError(
            "episode uncross aborted: fill log overflow — raise "
            "EngineConfig.max_fills for this population")

    cfg = spec.cfg
    bld = OpfileBuilder(cfg.num_symbols, spec.mix, episode_roles(spec),
                        serve_shards=serve_shards,
                        symbol_prefix=symbol_prefix)
    op, side, otype = lanes[..., 0], lanes[..., 1], lanes[..., 2]
    price, qty, oid = lanes[..., 3], lanes[..., 4], lanes[..., 5]
    fills = np.asarray(stats.fills)[:ep_len, venue]
    volume = np.asarray(stats.volume)[:ep_len, venue]
    un_hi = np.asarray(stats.uncross_hi)[:ep_len, venue].astype(np.int64)
    un_lo = np.asarray(stats.uncross_lo)[:ep_len, venue].astype(np.int64)

    manifest_phases = []
    step0 = 0
    for ph in scenario.phases:
        start_rec = len(bld.records)
        end = step0 + ph.steps
        for t in range(step0, end):
            bld.add_step(t, op[t], side[t], otype[t], price[t], qty[t],
                         oid[t])
        manifest_phases.append({
            "kind": ph.kind,
            "steps": ph.steps,
            "start_record": start_rec,
            "end_record": len(bld.records),
            "fills": int(fills[step0:end].sum()),
            "volume": int(volume[step0:end].sum()),
            "uncross": ph.kind == "auction",
            "uncross_executed": int((un_hi[end - 1] << 15)
                                    + un_lo[end - 1]),
        })
        step0 = end

    bld.write(out_path)

    mix = spec.mix
    manifest = {
        "format": MANIFEST_FORMAT,
        "name": scenario.name,
        "seed": seed,
        "symbols": cfg.num_symbols,
        "capacity": cfg.capacity,
        "batch": spec.lanes(),
        "kernel": cfg.kernel,
        "max_fills": cfg.max_fills,
        "serve_shards": serve_shards,
        "zipf_alpha_q8": scenario.zipf_alpha_q8,
        "steps": ep_len,
        "phases": manifest_phases,
        **bld.manifest_accounting(),
        "sim_fills": sum(p["fills"] for p in manifest_phases),
        "sim_volume": sum(p["volume"] for p in manifest_phases),
        "agent_mix": {
            "mm_agents": mix.mm_agents, "mm_refresh": mix.mm_refresh,
            "momentum": mix.momentum, "noise": mix.noise,
            "takers": mix.takers,
        },
        "source": "gym",
        "venue": venue,
        "episode": episode,
        "action_slots": spec.action_slots,
    }
    with open(manifest_path_for(out_path), "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    if metrics is not None:
        metrics.inc("gym_episodes_frozen")
        metrics.inc("gym_frozen_ops", len(bld.records))
    return manifest
