"""ctypes bindings for the C++ runtime layer (native/me_native.cpp).

The reference is an all-C++ gateway; this package is where the new
framework's host runtime stays native: Q4 price arithmetic, the MPSC
op ring behind the batch dispatcher, and the async SQLite sink. Each
binding has a pure-Python twin (domain/price.py, server/dispatcher.py,
storage/async_sink.py) — the native path is selected when the library is
present, and parity between the two is enforced by tests/test_native.py.

`ensure_built()` compiles the library on demand (g++ + system libsqlite3;
nothing to pip-install). `available()` gates call sites.
"""

from __future__ import annotations

import ctypes
import os
import shutil
import struct
import subprocess
import threading

_PKG_DIR = os.path.dirname(__file__)
_LIB_PATH = os.path.join(_PKG_DIR, "libme_native.so")
_SRC_DIR = os.path.normpath(os.path.join(_PKG_DIR, "..", "..", "native"))
_SRC = os.path.join(_SRC_DIR, "me_native.cpp")

_lib = None
_lib_lock = threading.Lock()

# me_validate_submit codes -> the service's reject messages
# (reference matching_engine_service.cpp:66-83 wording preserved upstream).
VALIDATE_MESSAGES = {
    1: "symbol is required",
    2: "quantity must be positive",
    3: "price must be positive for LIMIT orders",
    4: "scale out of range [0, 18]",
    5: "price overflows the engine's Q4 range",
    6: "quantity exceeds the engine maximum",
    7: "side must be BUY or SELL",
    8: "order_type must be LIMIT or MARKET",
    9: "symbol too long",
    10: "client_id too long",
}


class MeOp(ctypes.Structure):
    _fields_ = [
        ("tag", ctypes.c_uint64),
        ("sym", ctypes.c_int32),
        ("op", ctypes.c_int32),
        ("side", ctypes.c_int32),
        ("otype", ctypes.c_int32),
        ("price", ctypes.c_int32),
        ("qty", ctypes.c_int32),
        ("oid", ctypes.c_int32),
        ("pad", ctypes.c_int32),
    ]


_SRCS = [_SRC, os.path.join(_SRC_DIR, "me_lanes.cpp"),
         os.path.join(_SRC_DIR, "me_shmring.cpp"),
         os.path.join(_SRC_DIR, "me_gwop.h")]


def ensure_built(force: bool = False) -> bool:
    """Build the native layer if missing or stale. Returns availability
    of libme_native.so (the lane/ring/sink layer).

    The full make (gateway library + CLI client) runs only when protoc is
    on PATH — it needs the generated pb. Without protoc only the
    protobuf-free `native-lib` target builds, and a full-make failure
    falls back to it so a broken protobuf toolchain can never block the
    lane/ring/sink layer (scripts/build_native.sh is the explicit rebuild
    entry point)."""
    have_protoc = shutil.which("protoc") is not None
    if os.path.exists(_LIB_PATH) and not force:
        srcs = [s for s in _SRCS if os.path.exists(s)]
        lib_mtime = os.path.getmtime(_LIB_PATH)
        # Gateway staleness rides the same check — but only when a
        # rebuild could actually freshen it (protoc present); otherwise a
        # stale gateway lib would spawn a futile make on every load.
        gw_src = os.path.join(_SRC_DIR, "me_gateway.cpp")
        if (have_protoc and os.path.exists(_GW_LIB_PATH)
                and os.path.exists(gw_src)):
            srcs = srcs + [gw_src]
            lib_mtime = min(lib_mtime, os.path.getmtime(_GW_LIB_PATH))
        if not srcs or all(lib_mtime >= os.path.getmtime(s) for s in srcs):
            return True
    if not os.path.exists(_SRC):
        return os.path.exists(_LIB_PATH)
    targets = ["all", "native-lib"] if have_protoc else ["native-lib"]
    for target in targets:
        try:
            subprocess.run(
                ["make", "-s", target], cwd=_SRC_DIR, check=True,
                capture_output=True,
            )
            return True
        except (subprocess.CalledProcessError, FileNotFoundError) as e:
            out = getattr(e, "stderr", b"") or b""
            print(f"[native] build ({target}) failed: "
                  f"{out.decode(errors='replace')[-500:]}")
    return os.path.exists(_LIB_PATH)


def _load():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        # ME_NATIVE_LIB points the whole wrapper stack at an alternate
        # build of libme_native.so — the sanitizer smoke (ASan/UBSan
        # variants from scripts/build_native.sh --sanitize=...) runs the
        # codec/ring/lane fuzz through the same python surface it
        # normally serves. No staleness check: the override owner built
        # it deliberately.
        override = os.environ.get("ME_NATIVE_LIB")
        if override:
            # An explicit override must fail LOUDLY: silently falling
            # back to the default (or pure-python) runtime would let a
            # sanitizer run believe it tested an instrumented build it
            # never loaded. available() maps any OSError (including
            # this FileNotFoundError) to False for callers that probe.
            if not os.path.exists(override):
                raise FileNotFoundError(
                    f"ME_NATIVE_LIB={override} does not exist")
            lib = ctypes.CDLL(override)
        else:
            if not ensure_built():
                return None
            lib = ctypes.CDLL(_LIB_PATH)
        lib.me_normalize_to_q4.argtypes = [
            ctypes.c_longlong, ctypes.c_int, ctypes.POINTER(ctypes.c_longlong)
        ]
        lib.me_normalize_to_q4.restype = ctypes.c_int
        lib.me_validate_submit.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_longlong, ctypes.c_int,
            ctypes.c_int, ctypes.c_longlong, ctypes.c_int,
            ctypes.c_longlong, ctypes.c_longlong, ctypes.c_int, ctypes.c_int,
        ]
        lib.me_validate_submit.restype = ctypes.c_int

        lib.me_ring_create.argtypes = [ctypes.c_uint32]
        lib.me_ring_create.restype = ctypes.c_void_p
        lib.me_ring_destroy.argtypes = [ctypes.c_void_p]
        lib.me_ring_push.argtypes = [ctypes.c_void_p, ctypes.POINTER(MeOp)]
        lib.me_ring_push.restype = ctypes.c_int
        lib.me_ring_pop_batch.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(MeOp), ctypes.c_uint32,
            ctypes.c_uint64,
        ]
        lib.me_ring_pop_batch.restype = ctypes.c_int
        lib.me_ring_pop_batch_timed.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(MeOp), ctypes.c_uint32,
            ctypes.c_uint64, ctypes.c_int64,
        ]
        lib.me_ring_pop_batch_timed.restype = ctypes.c_int
        lib.me_ring_close.argtypes = [ctypes.c_void_p]
        lib.me_ring_dropped.argtypes = [ctypes.c_void_p]
        lib.me_ring_dropped.restype = ctypes.c_uint64
        lib.me_ring_size.argtypes = [ctypes.c_void_p]
        lib.me_ring_size.restype = ctypes.c_uint64

        _bind_lanes(lib)
        lib.me_sink_open.argtypes = [ctypes.c_char_p, ctypes.c_uint32]
        lib.me_sink_open.restype = ctypes.c_void_p
        lib.me_sink_submit.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int
        ]
        lib.me_sink_submit.restype = ctypes.c_int
        lib.me_sink_flush.argtypes = [ctypes.c_void_p]
        lib.me_sink_stats.argtypes = [ctypes.c_void_p] + [
            ctypes.POINTER(ctypes.c_uint64)
        ] * 4
        lib.me_sink_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    try:
        return _load() is not None
    except OSError:
        return False


# -- domain -----------------------------------------------------------------

def normalize_to_q4(price: int, raw_scale: int) -> int:
    """Native twin of domain.price.normalize_to_q4 (same raise behavior)."""
    from matching_engine_tpu.domain.price import PriceError

    lib = _load()
    out = ctypes.c_longlong()
    rc = lib.me_normalize_to_q4(price, raw_scale, ctypes.byref(out))
    if rc == 1:
        raise PriceError(f"scale {raw_scale} out of range [0, 18]")
    if rc == 2:
        raise PriceError(
            f"price {price} at scale {raw_scale} overflows int64 when "
            f"normalized to Q4"
        )
    return out.value


def validate_submit_code(
    symbol_len: int, client_id_len: int, quantity: int, side: int,
    order_type: int, price: int, scale: int,
) -> int:
    """0 = valid; else a VALIDATE_MESSAGES key. Bounds come from the domain
    constants so native and Python validation can never drift."""
    from matching_engine_tpu.domain.order import (
        MAX_CLIENT_ID_BYTES,
        MAX_QUANTITY,
        MAX_SYMBOL_BYTES,
    )
    from matching_engine_tpu.domain.price import MAX_DEVICE_PRICE_Q4

    return _load().me_validate_submit(
        symbol_len, client_id_len, quantity, side, order_type, price, scale,
        MAX_DEVICE_PRICE_Q4, MAX_QUANTITY, MAX_SYMBOL_BYTES,
        MAX_CLIENT_ID_BYTES,
    )


# -- ring -------------------------------------------------------------------

class NativeRing:
    """Bounded MPSC op ring; the batching window runs in C++ off the GIL."""

    def __init__(self, capacity: int = 1 << 16):
        self._lib = _load()
        self._h = self._lib.me_ring_create(capacity)
        if not self._h:
            raise RuntimeError("me_ring_create failed")
        self._buf = None  # reused pop buffer (single consumer)

    def push(self, tag: int, sym: int, op: int, side: int, otype: int,
             price: int, qty: int, oid: int) -> bool:
        if self._h is None:  # destroyed ring: behave as closed, never segv
            return False
        rec = MeOp(tag=tag, sym=sym, op=op, side=side, otype=otype,
                   price=price, qty=qty, oid=oid, pad=0)
        return bool(self._lib.me_ring_push(self._h, ctypes.byref(rec)))

    def pop_batch(self, max_ops: int, window_us: int,
                  first_wait_us: int = -1):
        """Blocks for the first op (bounded when first_wait_us >= 0), then
        drains up to (max_ops, window_us). Returns a list of MeOp field
        tuples, [] on first-wait timeout, or None when closed+empty.

        The output buffer is allocated once and reused — the ring has a
        single consumer, and max_ops can be thousands of 40-byte records per
        ~2ms drain window."""
        if self._h is None:
            return None
        buf = self._buf
        if buf is None or len(buf) < max_ops:
            buf = self._buf = (MeOp * max_ops)()
        n = self._lib.me_ring_pop_batch_timed(self._h, buf, max_ops,
                                              window_us, first_wait_us)
        if n < 0:
            return None
        return [
            (r.tag, r.sym, r.op, r.side, r.otype, r.price, r.qty, r.oid)
            for r in buf[:n]
        ]

    def close(self) -> None:
        if self._h is not None:
            self._lib.me_ring_close(self._h)

    def destroy(self) -> None:
        if self._h:
            self._lib.me_ring_destroy(self._h)
            self._h = None

    @property
    def dropped(self) -> int:
        return 0 if self._h is None else self._lib.me_ring_dropped(self._h)

    def __len__(self) -> int:
        return 0 if self._h is None else self._lib.me_ring_size(self._h)


# -- gateway ----------------------------------------------------------------

_GW_LIB_PATH = os.path.join(_PKG_DIR, "libme_gateway.so")
_CLIENT_PATH = os.path.join(_PKG_DIR, "me_client")
_gw_lib = None

# Python mirror of MeGwOp (native/me_gateway.cpp) — keep layouts identical.
# Strings are length-prefixed (embedded NULs round-trip like the grpcio edge).
class MeGwOp(ctypes.Structure):
    _fields_ = [
        ("tag", ctypes.c_uint64),
        ("op", ctypes.c_int32),        # 1 submit / 2 cancel
        ("side", ctypes.c_int32),
        ("otype", ctypes.c_int32),
        ("price_q4", ctypes.c_int32),
        ("quantity", ctypes.c_int64),
        ("symbol_len", ctypes.c_int32),
        ("client_id_len", ctypes.c_int32),
        ("order_id_len", ctypes.c_int32),
        ("symbol", ctypes.c_char * 68),
        ("client_id", ctypes.c_char * 260),
        ("order_id", ctypes.c_char * 36),
    ]


# Python mirror of MeShmResp (native/me_gwop.h) — one positional response
# record on the shm ingress ring; oprec.SHM_RESP_DTYPE is the numpy twin
# and the ABI cross-checker (analysis/abi.py) pins all three layouts.
class MeShmResp(ctypes.Structure):
    _fields_ = [
        ("seq", ctypes.c_uint64),
        ("remaining", ctypes.c_int64),
        ("order_id", ctypes.c_char * 24),
        ("ok", ctypes.c_uint8),
        ("kind", ctypes.c_uint8),
        ("reason", ctypes.c_uint8),
        ("oid_len", ctypes.c_uint8),
        # Writer lane echoed from the request record (per-writer response
        # demux — see MeShmResp in native/me_gwop.h).
        ("writer", ctypes.c_uint8),
        ("pad", ctypes.c_char * 3),
    ]


GW_CALLBACK = ctypes.CFUNCTYPE(
    None, ctypes.c_uint64, ctypes.c_int, ctypes.POINTER(ctypes.c_uint8),
    ctypes.c_uint64,
)

# Forwarded-method ids (me_gateway.cpp Method enum).
(GW_SUBMIT, GW_CANCEL, GW_BOOK, GW_METRICS, GW_STREAM_MD, GW_STREAM_OU,
 GW_AUCTION) = range(1, 8)
GW_BATCH = 9  # SubmitOrderBatch (M_AMEND=8 is a hot-path id, not forwarded)


def _load_gateway():
    global _gw_lib
    with _lib_lock:
        if _gw_lib is not None:
            return _gw_lib
        if not ensure_built():
            return None
        if not os.path.exists(_GW_LIB_PATH):
            return None
        lib = ctypes.CDLL(_GW_LIB_PATH)
        lib.me_gateway_create.argtypes = [
            ctypes.c_char_p, ctypes.c_uint32, ctypes.c_longlong,
            ctypes.c_longlong, ctypes.c_int, ctypes.c_int,
        ]
        lib.me_gateway_create.restype = ctypes.c_void_p
        lib.me_gateway_start.argtypes = [ctypes.c_void_p]
        lib.me_gateway_start.restype = ctypes.c_int
        lib.me_gateway_port.argtypes = [ctypes.c_void_p]
        lib.me_gateway_port.restype = ctypes.c_int
        lib.me_gateway_set_callback.argtypes = [ctypes.c_void_p, GW_CALLBACK]
        try:
            lib.me_gateway_set_forward_batch.argtypes = [
                ctypes.c_void_p, ctypes.c_int,
            ]
        except AttributeError:
            # A stale pre-batch-path build: the native M_BATCH path is
            # simply always-forward there (the python wrapper guards).
            pass
        lib.me_gw_pop_batch_timed.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(MeGwOp), ctypes.c_uint32,
            ctypes.c_uint64, ctypes.c_int64,
        ]
        lib.me_gw_pop_batch_timed.restype = ctypes.c_int
        lib.me_gw_pop_batch.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(MeGwOp), ctypes.c_uint32,
            ctypes.c_uint64,
        ]
        lib.me_gw_pop_batch.restype = ctypes.c_int
        lib.me_gateway_complete_submit.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int, ctypes.c_char_p,
            ctypes.c_char_p,
        ]
        lib.me_gateway_complete_cancel.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int, ctypes.c_char_p,
            ctypes.c_char_p,
        ]
        lib.me_gateway_complete_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
        ]
        lib.me_gateway_complete_amend.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int, ctypes.c_char_p,
            ctypes.c_longlong, ctypes.c_char_p,
        ]
        lib.me_gateway_respond.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p,
            ctypes.c_uint64, ctypes.c_int, ctypes.c_int, ctypes.c_char_p,
        ]
        lib.me_gateway_respond.restype = ctypes.c_int
        lib.me_gateway_stream_alive.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.me_gateway_stream_alive.restype = ctypes.c_int
        lib.me_gateway_stats.argtypes = [ctypes.c_void_p] + [
            ctypes.POINTER(ctypes.c_uint64)
        ] * 3
        lib.me_gateway_shutdown.argtypes = [ctypes.c_void_p]
        lib.me_gateway_destroy.argtypes = [ctypes.c_void_p]
        _gw_lib = lib
        return _gw_lib


def gateway_available() -> bool:
    try:
        return _load_gateway() is not None
    except OSError:
        return False


def client_binary() -> str | None:
    """Path to the native CLI client, if built."""
    ensure_built()
    return _CLIENT_PATH if os.path.exists(_CLIENT_PATH) else None


class NativeGateway:
    """The C++ gRPC serving edge (native/me_gateway.cpp).

    Hot-path ops (submit/cancel) surface through `pop_batch` as wide
    records and are answered with `complete_*`; forwarded methods
    (book/metrics/streams) arrive via the registered callback and are
    answered with `respond`.
    """

    def __init__(self, addr: str = "0.0.0.0:0", ring_capacity: int = 1 << 15):
        from matching_engine_tpu.domain.order import (
            MAX_CLIENT_ID_BYTES,
            MAX_QUANTITY,
            MAX_SYMBOL_BYTES,
        )
        from matching_engine_tpu.domain.price import MAX_DEVICE_PRICE_Q4

        lib = _load_gateway()
        if lib is None:
            raise RuntimeError("native gateway library unavailable")
        self._lib = lib
        self._h = lib.me_gateway_create(
            addr.encode(), ring_capacity, MAX_DEVICE_PRICE_Q4, MAX_QUANTITY,
            MAX_SYMBOL_BYTES, MAX_CLIENT_ID_BYTES,
        )
        if not self._h:
            raise RuntimeError("me_gateway_create failed")
        self._cb_ref = None  # keep the CFUNCTYPE object alive
        self._buf = None
        self.port = -1

    def start(self) -> int:
        port = self._lib.me_gateway_start(self._h)
        if port < 0:
            raise RuntimeError("native gateway failed to bind")
        self.port = port
        return port

    def set_callback(self, fn) -> None:
        """fn(tag: int, method: int, payload: bytes); runs on a C++
        connection thread (ctypes acquires the GIL) — must not block."""

        def _trampoline(tag, method, data, length):
            try:
                payload = ctypes.string_at(data, length) if length else b""
                fn(tag, method, payload)
            except Exception as e:  # noqa: BLE001 — never unwind into C++
                print(f"[gateway] callback error: {type(e).__name__}: {e}")

        self._cb_ref = GW_CALLBACK(_trampoline)
        self._lib.me_gateway_set_callback(self._h, self._cb_ref)

    def set_forward_batch(self, forward: bool) -> None:
        """M_BATCH routing: False (default) = the in-gateway native
        batch path (me_oprec_flaws + me_oprec_to_gwop + ring_push_n,
        answered positionally from ring completions); True = forward the
        payload through the python callback into the shared service
        handler (the bridge sets this when the vectorized admission
        screens are enabled — those run python-side)."""
        fn = getattr(self._lib, "me_gateway_set_forward_batch", None)
        if fn is None:
            return  # stale build: M_BATCH always forwards there
        fn(self._h, 1 if forward else 0)

    def pop_batch(self, max_ops: int, window_us: int,
                  first_wait_us: int = -1):
        """Blocks for the first op (bounded when first_wait_us >= 0),
        drains to (max_ops, window_us). Returns a list of (tag, op, side,
        otype, price_q4, quantity, symbol, client_id, order_id), [] on
        first-wait timeout, or None when shut down."""
        if self._h is None:
            return None
        buf = self._buf
        if buf is None or len(buf) < max_ops:
            buf = self._buf = (MeGwOp * max_ops)()
        n = self._lib.me_gw_pop_batch_timed(self._h, buf, max_ops,
                                            window_us, first_wait_us)
        if n < 0:
            return None
        out = []
        for r in buf[:n]:
            try:
                out.append(
                    (r.tag, r.op, r.side, r.otype, r.price_q4, r.quantity,
                     bytes(r.symbol[:r.symbol_len]).decode(),
                     bytes(r.client_id[:r.client_id_len]).decode(),
                     bytes(r.order_id[:r.order_id_len]).decode())
                )
            except UnicodeDecodeError:
                # Per-record failure: a hostile payload surviving the C++
                # parse must poison only ITS op, never the batch — the
                # bridge rejects string-fields-None records individually.
                out.append((r.tag, r.op, r.side, r.otype, r.price_q4,
                            r.quantity, None, None, None))
        return out

    def pop_batch_raw(self, max_ops: int, window_us: int,
                      first_wait_us: int = -1):
        """pop_batch WITHOUT per-record Python decode: returns
        (records_array, n) for the native lane path (the array is reused
        across pops — single consumer), n == 0 on first-wait timeout,
        (None, 0) when shut down."""
        if self._h is None:
            return None, 0
        buf = self._buf
        if buf is None or len(buf) < max_ops:
            buf = self._buf = (MeGwOp * max_ops)()
        n = self._lib.me_gw_pop_batch_timed(self._h, buf, max_ops,
                                            window_us, first_wait_us)
        if n < 0:
            return None, 0
        return buf, n

    def complete_batch_raw(self, buf: bytes) -> None:
        """complete_batch for an ALREADY-PACKED completion buffer (the
        lane engine's comp_buf is emitted in this wire format)."""
        if self._h is None or len(buf) <= 4:
            return
        self._lib.me_gateway_complete_batch(self._h, buf, len(buf))

    def complete_submit(self, tag: int, success: bool, order_id: str,
                        error: str = "") -> None:
        if self._h is None:
            return
        self._lib.me_gateway_complete_submit(
            self._h, tag, 1 if success else 0, order_id.encode(),
            error.encode(),
        )

    def complete_cancel(self, tag: int, success: bool, order_id: str,
                        error: str = "") -> None:
        if self._h is None:
            return
        self._lib.me_gateway_complete_cancel(
            self._h, tag, 1 if success else 0, order_id.encode(),
            error.encode(),
        )

    def complete_amend(self, tag: int, success: bool, order_id: str,
                       remaining: int = 0, error: str = "") -> None:
        if self._h is None:
            return
        self._lib.me_gateway_complete_amend(
            self._h, tag, 1 if success else 0, order_id.encode(),
            remaining, error.encode(),
        )

    def complete_batch(
        self, items: list[tuple[int, int, bool, str, str]]
    ) -> None:
        """One ctypes crossing for a whole dispatch's completions.

        items: (tag, kind 0=submit/1=cancel, success, order_id, error).
        The C++ side groups by connection and writes each connection's
        response frames with a single locked send (me_gateway.cpp
        me_gateway_complete_batch — the wire format lives there).
        """
        if self._h is None or not items:
            return
        out = bytearray(struct.pack("<I", len(items)))
        for (tag, kind, success, order_id, error) in items:
            oid = order_id.encode()
            err = error.encode()
            out += struct.pack("<QBBH", tag, kind, 1 if success else 0,
                               len(oid))
            out += oid
            out += struct.pack("<H", len(err))
            out += err
        buf = bytes(out)
        self._lib.me_gateway_complete_batch(self._h, buf, len(buf))

    def respond(self, tag: int, msg: bytes | None, end_stream: bool,
                grpc_status: int = 0, grpc_message: str = "") -> bool:
        if self._h is None:
            return False
        return bool(self._lib.me_gateway_respond(
            self._h, tag, msg, len(msg) if msg else 0,
            1 if end_stream else 0, grpc_status, grpc_message.encode(),
        ))

    def stream_alive(self, tag: int) -> bool:
        if self._h is None:
            return False
        return bool(self._lib.me_gateway_stream_alive(self._h, tag))

    def stats(self) -> dict:
        if self._h is None:
            return {"requests": 0, "ring_rejects": 0, "conns": 0}
        vals = [ctypes.c_uint64() for _ in range(3)]
        self._lib.me_gateway_stats(self._h, *[ctypes.byref(v) for v in vals])
        return {
            "requests": vals[0].value,
            "ring_rejects": vals[1].value,
            "conns": vals[2].value,
        }

    def shutdown(self) -> None:
        if self._h is not None:
            self._lib.me_gateway_shutdown(self._h)

    def destroy(self) -> None:
        if self._h:
            self._lib.me_gateway_destroy(self._h)
            self._h = None


# -- sink -------------------------------------------------------------------

def _pack_str(out: bytearray, s: str) -> None:
    b = s.encode()
    out += struct.pack("<H", len(b))
    out += b


def pack_batch(orders, updates, fills) -> bytes:
    """Serialize one dispatch for MeSink (format in me_native.cpp §3).

    orders: (order_id, client_id, symbol, side, collapsed_otype,
             price|None, qty, remaining, status) — field 5 is the engine's
             collapsed (order_type, tif) lane code (proto.split_otype);
             MeSink splits it into the order_type column (wire 0/1) and
             the tif column, mirroring Storage.apply_batch;
    updates: (order_id, status, remaining); fills: FillRow.
    """
    out = bytearray()
    out += struct.pack("<I", len(orders))
    for (oid, cid, sym, side, otype, price, qty, remaining, status) in orders:
        _pack_str(out, oid)
        _pack_str(out, cid)
        _pack_str(out, sym)
        out += struct.pack(
            "<BBBqqqB", side, otype, 0 if price is None else 1,
            price or 0, qty, remaining, status,
        )
    out += struct.pack("<I", len(updates))
    for u in updates:
        # 3-tuple: status/remaining update. 4-tuple: amend — also moves
        # quantity (has_qty flag byte; MeSink binds the amend statement).
        _pack_str(out, u[0])
        if len(u) == 3:
            out += struct.pack("<BqBq", u[1], u[2], 0, 0)
        else:
            out += struct.pack("<BqBq", u[1], u[2], 1, u[3])
    out += struct.pack("<I", len(fills))
    for f in fills:
        _pack_str(out, f.order_id)
        _pack_str(out, f.counter_order_id)
        out += struct.pack("<qqq", f.price_q4, f.quantity, f.ts)
    return bytes(out)


class NativeStorageSink:
    """Drop-in for storage.AsyncStorageSink backed by the C++ worker.

    Row-for-row identical SQLite output (enforced by tests/test_native.py);
    serialization happens on the caller's thread, SQLite work on the C++
    thread — the GIL is held only while packing bytes.
    """

    def __init__(self, db_path: str, max_queue: int = 4096):
        d = os.path.dirname(db_path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._lib = _load()
        self._h = self._lib.me_sink_open(db_path.encode(), max_queue)
        if not self._h:
            raise RuntimeError(f"me_sink_open({db_path}) failed")
        self.dropped = 0

    def submit(self, orders=None, updates=None, fills=None, block=True) -> bool:
        if self._h is None:
            return False
        buf = pack_batch(orders or [], updates or [], fills or [])
        if len(buf) <= 12:  # three zero counts — nothing to write
            return True
        ok = bool(self._lib.me_sink_submit(
            self._h, buf, len(buf), 1 if block else 0
        ))
        if not ok:
            self.dropped += 1
        return ok

    def submit_packed(self, buf: bytes, block: bool = True) -> bool:
        """Submit an ALREADY-PACKED MeSink batch (the lane engine's
        store_buf is emitted in this wire format — zero Python tuples on
        the native serving path)."""
        if self._h is None:
            return False
        if len(buf) <= 12:
            return True
        ok = bool(self._lib.me_sink_submit(
            self._h, buf, len(buf), 1 if block else 0
        ))
        if not ok:
            self.dropped += 1
        return ok

    def flush(self) -> None:
        if self._h is not None:
            self._lib.me_sink_flush(self._h)

    def stats(self) -> dict:
        vals = [ctypes.c_uint64() for _ in range(4)]
        if self._h is None:
            return {"batches": 0, "rows": 0, "dropped": 0, "errors": 0}
        self._lib.me_sink_stats(self._h, *[ctypes.byref(v) for v in vals])
        return {
            "batches": vals[0].value, "rows": vals[1].value,
            "dropped": vals[2].value, "errors": vals[3].value,
        }

    def close(self) -> None:
        if self._h:
            self._lib.me_sink_close(self._h)
            self._h = None


# -- lane engine (native/me_lanes.cpp) --------------------------------------
#
# The native serving fast path: lane build + completion decode in C++,
# leaving Python control-plane work per DISPATCH. The Python twin is
# gateway_bridge._drain_batch + engine_runner._stage_locked/_decode_batch/
# _evict_terminal; tests/test_native_lanes.py enforces bit-parity.

def _bind_lanes(lib) -> None:
    P = ctypes.POINTER
    i32p, i64p, u8p = P(ctypes.c_int32), P(ctypes.c_longlong), P(ctypes.c_uint8)
    lib.me_lanes_create.argtypes = [ctypes.c_int32] * 4
    lib.me_lanes_create.restype = ctypes.c_void_p
    lib.me_lanes_destroy.argtypes = [ctypes.c_void_p]
    lib.me_lanes_build.argtypes = [
        ctypes.c_void_p, P(MeGwOp), ctypes.c_uint32, ctypes.c_int,
        ctypes.c_int, i32p, i32p, i32p, ctypes.c_uint32,
    ]
    lib.me_lanes_build.restype = ctypes.c_int
    lib.me_lanes_wave.argtypes = [ctypes.c_void_p, ctypes.c_uint32, i32p]
    lib.me_lanes_wave.restype = ctypes.c_int
    lib.me_lanes_wave_mega.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint32, i32p,
    ]
    lib.me_lanes_wave_mega.restype = ctypes.c_int
    lib.me_lanes_decode_wave.argtypes = [
        ctypes.c_void_p, i32p, ctypes.c_longlong, i32p, ctypes.c_longlong,
    ]
    lib.me_lanes_decode_wave.restype = ctypes.c_longlong
    lib.me_lanes_decode_mega.argtypes = [
        ctypes.c_void_p, i32p, ctypes.c_longlong, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int32, i32p, ctypes.c_longlong,
    ]
    lib.me_lanes_decode_mega.restype = ctypes.c_longlong
    lib.me_lanes_finish.argtypes = [ctypes.c_void_p, i64p, i64p, i64p]
    lib.me_lanes_finish.restype = ctypes.c_int
    lib.me_lanes_take.argtypes = [ctypes.c_void_p, u8p, u8p, u8p]
    lib.me_lanes_take.restype = ctypes.c_int
    lib.me_lanes_abort.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.me_lanes_abort.restype = ctypes.c_int
    lib.me_lanes_get_order.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, i64p, i32p, i64p,
        ctypes.c_char_p, i32p, ctypes.c_char_p, i32p,
    ]
    lib.me_lanes_get_order.restype = ctypes.c_int
    lib.me_lanes_lookup.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32,
    ]
    lib.me_lanes_lookup.restype = ctypes.c_int32
    lib.me_lanes_adjust.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_longlong, ctypes.c_int32,
    ]
    lib.me_lanes_adjust.restype = ctypes.c_int
    lib.me_lanes_evict.argtypes = [ctypes.c_void_p, ctypes.c_int32, i32p]
    lib.me_lanes_evict.restype = ctypes.c_int
    lib.me_lanes_set_auction_mode.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.me_lanes_set_oid_stride.argtypes = [ctypes.c_void_p,
                                            ctypes.c_longlong]
    lib.me_lanes_adopt.argtypes = [ctypes.c_void_p, u8p, ctypes.c_longlong]
    lib.me_lanes_adopt.restype = ctypes.c_int
    lib.me_lanes_dump_slots.argtypes = [
        ctypes.c_void_p, u8p, ctypes.c_longlong,
    ]
    lib.me_lanes_dump_slots.restype = ctypes.c_longlong
    lib.me_lanes_dump_state.argtypes = [
        ctypes.c_void_p, u8p, ctypes.c_longlong,
    ]
    lib.me_lanes_dump_state.restype = ctypes.c_longlong
    lib.me_lanes_stats.argtypes = [ctypes.c_void_p, i64p, i64p, i64p]

    lib.me_gwring_create.argtypes = [ctypes.c_uint32]
    lib.me_gwring_create.restype = ctypes.c_void_p
    lib.me_gwring_destroy.argtypes = [ctypes.c_void_p]
    lib.me_gwring_push.argtypes = [ctypes.c_void_p, P(MeGwOp)]
    lib.me_gwring_push.restype = ctypes.c_int
    lib.me_gwring_push_n.argtypes = [
        ctypes.c_void_p, P(MeGwOp), ctypes.c_uint32,
    ]
    lib.me_gwring_push_n.restype = ctypes.c_int
    lib.me_oprec_to_gwop.argtypes = [
        ctypes.c_char_p, ctypes.c_longlong, ctypes.c_uint64, P(MeGwOp),
        ctypes.c_uint32,
    ]
    lib.me_oprec_to_gwop.restype = ctypes.c_int
    lib.me_gwring_pop_batch.argtypes = [
        ctypes.c_void_p, P(MeGwOp), ctypes.c_uint32, ctypes.c_uint64,
        ctypes.c_int64,
    ]
    lib.me_gwring_pop_batch.restype = ctypes.c_int
    lib.me_gwring_close.argtypes = [ctypes.c_void_p]
    lib.me_gwring_dropped.argtypes = [ctypes.c_void_p]
    lib.me_gwring_dropped.restype = ctypes.c_uint64
    lib.me_oprec_flaws.argtypes = [
        ctypes.c_char_p, ctypes.c_longlong, ctypes.c_longlong,
        ctypes.c_longlong, ctypes.POINTER(ctypes.c_int32), ctypes.c_uint32,
    ]
    lib.me_oprec_flaws.restype = ctypes.c_int

    # Shared-memory ingress ring (native/me_shmring.cpp).
    lib.me_shmring_create.argtypes = [
        ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint32,
    ]
    lib.me_shmring_create.restype = ctypes.c_void_p
    lib.me_shmring_attach.argtypes = [ctypes.c_char_p]
    lib.me_shmring_attach.restype = ctypes.c_void_p
    lib.me_shmring_close.argtypes = [ctypes.c_void_p]
    lib.me_shmring_shutdown.argtypes = [ctypes.c_void_p]
    lib.me_shmring_claim.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.me_shmring_claim.restype = ctypes.c_longlong
    lib.me_shmring_slot.argtypes = [ctypes.c_void_p, ctypes.c_longlong]
    lib.me_shmring_slot.restype = ctypes.c_void_p
    lib.me_shmring_commit.argtypes = [ctypes.c_void_p, ctypes.c_longlong]
    lib.me_shmring_wake.argtypes = [ctypes.c_void_p]
    lib.me_shmring_push_n.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
    ]
    lib.me_shmring_push_n.restype = ctypes.c_longlong
    lib.me_shmring_poll.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, i64p, ctypes.c_uint32,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, i64p,
    ]
    lib.me_shmring_poll.restype = ctypes.c_int
    lib.me_shmring_respond_n.argtypes = [
        ctypes.c_void_p, P(MeShmResp), ctypes.c_uint32,
    ]
    lib.me_shmring_respond_n.restype = ctypes.c_int
    lib.me_shmring_resp_poll.argtypes = [
        ctypes.c_void_p, P(MeShmResp), ctypes.c_uint32, ctypes.c_int64,
    ]
    lib.me_shmring_resp_poll.restype = ctypes.c_int
    lib.me_shmring_stats.argtypes = [ctypes.c_void_p, i64p, i64p, i64p, i64p]
    lib.me_shmring_register.argtypes = [ctypes.c_void_p]
    lib.me_shmring_register.restype = ctypes.c_int
    lib.me_shmring_deregister.argtypes = [ctypes.c_void_p]
    lib.me_shmring_writer_id.argtypes = [ctypes.c_void_p]
    lib.me_shmring_writer_id.restype = ctypes.c_int
    lib.me_shmring_writer_count.argtypes = [ctypes.c_void_p]
    lib.me_shmring_writer_count.restype = ctypes.c_int


def oprec_flaw_codes(body: bytes, n: int, max_price_q4: int,
                     max_quantity: int) -> list[int]:
    """Native twin of domain/oprec.record_flaws over a packed run (no
    magic): per-record flaw CODES (0 = clean; codes index the same
    branches record_flaws reports as messages — oprec.FLAW_MESSAGES maps
    back). The C++ gateway's M_BATCH path runs the identical function
    in-process; this wrapper exists for the parity test and any python
    caller that wants codes instead of strings."""
    lib = _load()
    out = (ctypes.c_int32 * max(1, n))()
    rc = lib.me_oprec_flaws(body, len(body), max_price_q4, max_quantity,
                            out, n)
    if rc != n:
        raise RuntimeError(f"me_oprec_flaws failed (rc={rc}, n={n})")
    return list(out[:n])


def oprec_to_gwop(body: bytes, n: int, tag_base: int):
    """Convert a packed op-record run (domain/oprec.py records, WITHOUT
    the magic header) into a tagged (MeGwOp * n) array in ONE native
    crossing: record i gets tag tag_base + i. Raises on structural skew
    (the edge pre-screens per-record flaws positionally, so a failure
    here is a caller bug, never client input)."""
    lib = _load()
    out = (MeGwOp * max(1, n))()
    rc = lib.me_oprec_to_gwop(body, len(body), tag_base, out, n)
    if rc != n:
        raise RuntimeError(f"me_oprec_to_gwop failed (rc={rc}, n={n})")
    return out


def pack_gwop(rec: MeGwOp, tag: int, op: int, side: int = 0, otype: int = 0,
              price_q4: int = 0, quantity: int = 0, symbol: bytes = b"",
              client_id: bytes = b"", order_id: bytes = b"") -> MeGwOp:
    """Fill one MeGwOp record in place (the ring/lane wire record)."""
    rec.tag = tag
    rec.op = op
    rec.side = side
    rec.otype = otype
    rec.price_q4 = price_q4
    rec.quantity = quantity
    rec.symbol_len = len(symbol)
    rec.client_id_len = len(client_id)
    rec.order_id_len = len(order_id)
    rec.symbol = symbol
    rec.client_id = client_id
    rec.order_id = order_id
    return rec


class _Rd:
    """Cursor over the little-endian length-prefixed aux/state wire."""

    __slots__ = ("b", "o")

    def __init__(self, b: bytes):
        self.b = b
        self.o = 0

    def u8(self) -> int:
        v = self.b[self.o]
        self.o += 1
        return v

    def u32(self) -> int:
        (v,) = struct.unpack_from("<I", self.b, self.o)
        self.o += 4
        return v

    def i32(self) -> int:
        (v,) = struct.unpack_from("<i", self.b, self.o)
        self.o += 4
        return v

    def u64(self) -> int:
        (v,) = struct.unpack_from("<Q", self.b, self.o)
        self.o += 8
        return v

    def i64(self) -> int:
        (v,) = struct.unpack_from("<q", self.b, self.o)
        self.o += 8
        return v

    def s(self) -> bytes:
        (n,) = struct.unpack_from("<H", self.b, self.o)
        self.o += 2
        v = self.b[self.o:self.o + n]
        self.o += n
        return v


LANE_COUNTER_NAMES = (
    "engine_ops", "accepted", "rejected", "canceled", "amended",
    "fill_count", "overflow_waves", "shape", "n_lanes", "n_waves",
    "owner_overflow", "owner_collisions", "n_recon",
)


def parse_comp_buf(buf: bytes) -> list[tuple[int, int, bool, str, str]]:
    """comp_buf records as (tag, kind, ok, order_id, error) — the
    me_gateway_complete_batch wire format (strings losslessly decoded;
    they were validated UTF-8 on the way in)."""
    r = _Rd(buf)
    out = []
    for _ in range(r.u32()):
        tag = r.u64()
        kind = r.u8()
        ok = r.u8() != 0
        oid = r.s().decode()
        err = r.s().decode()
        out.append((tag, kind, ok, oid, err))
    return out


def parse_lane_aux(buf: bytes) -> dict:
    """The per-dispatch aux buffer assembled by MeLanes::finish."""
    r = _Rd(buf)
    n_counters = r.u32()
    counters = {}
    for i in range(n_counters):
        v = r.i64()
        if i < len(LANE_COUNTER_NAMES):
            counters[LANE_COUNTER_NAMES[i]] = v
    out = {"counters": counters}
    out["slot_allocs"] = [(r.i32(), r.s().decode()) for _ in range(r.u32())]
    out["slot_releases"] = [r.i32() for _ in range(r.u32())]
    out["new_owners"] = [(r.s().decode(), r.i32()) for _ in range(r.u32())]
    out["recon"] = [(r.s().decode(), r.i64()) for _ in range(r.u32())]
    out["market_data"] = [
        (r.i32(), r.i32(), r.i32(), r.i32(), r.i32()) for _ in range(r.u32())
    ]  # (slot, best_bid, bid_size, best_ask, ask_size)
    out["amends"] = [
        (r.u64(), r.u8() != 0, r.i64(), r.s().decode(), r.s().decode())
        for _ in range(r.u32())
    ]  # (tag, ok, remaining, order_id, error)
    out["local"] = [
        (r.u64(), r.u8(), r.u8() != 0, r.i64(), r.s().decode(),
         r.s().decode())
        for _ in range(r.u32())
    ]  # (tag, kind, ok, remaining, order_id, error)
    out["order_updates"] = [
        (r.i32(), r.i64(), r.i64(), r.i64(), r.s().decode(),
         r.s().decode(), r.s().decode())
        for _ in range(r.u32())
    ]  # (status, fill_price, fill_qty, remaining, order_id, client_id, sym)
    return out


# unpack_store_buf's precompiled row tails (a _Rd method call per field
# costs ~7us/row in pure python; with --audit the drop-copy publisher
# unpacks every native dispatch's rows on the drain loop's publish path,
# so the parse runs one Struct per row instead).
_ST_U32 = struct.Struct("<I")
_ST_STR = struct.Struct("<H")
_ST_ORDER_TAIL = struct.Struct("<BBBqqqB")   # side otype has_price p q r st
_ST_UPDATE_TAIL = struct.Struct("<BqBq")     # status remaining has_qty qty
_ST_FILL_TAIL = struct.Struct("<qqq")        # price qty ts


def unpack_store_buf(buf: bytes):
    """store_buf -> the (orders, updates, fills) triple pack_batch packs —
    the Python-sink fallback, the storage-row parity check, and the
    --audit drop-copy source on the native path."""
    from matching_engine_tpu.storage.storage import FillRow

    o = 0
    u32, uS = _ST_U32.unpack_from, _ST_STR.unpack_from

    def rs(o: int) -> tuple[str, int]:
        (n,) = uS(buf, o)
        o += 2
        return buf[o:o + n].decode(), o + n

    (n,) = u32(buf, o)
    o += 4
    orders = []
    tail, tail_sz = _ST_ORDER_TAIL.unpack_from, _ST_ORDER_TAIL.size
    for _ in range(n):
        oid, o = rs(o)
        cid, o = rs(o)
        sym, o = rs(o)
        side, otype, has_price, price, qty, remaining, status = tail(buf, o)
        o += tail_sz
        orders.append((oid, cid, sym, side, otype,
                       price if has_price else None, qty, remaining, status))
    (n,) = u32(buf, o)
    o += 4
    updates = []
    tail, tail_sz = _ST_UPDATE_TAIL.unpack_from, _ST_UPDATE_TAIL.size
    for _ in range(n):
        oid, o = rs(o)
        status, remaining, has_qty, qty = tail(buf, o)
        o += tail_sz
        updates.append((oid, status, remaining, qty) if has_qty
                       else (oid, status, remaining))
    (n,) = u32(buf, o)
    o += 4
    fills = []
    tail, tail_sz = _ST_FILL_TAIL.unpack_from, _ST_FILL_TAIL.size
    for _ in range(n):
        oid, o = rs(o)
        coid, o = rs(o)
        price, qty, ts = tail(buf, o)
        o += tail_sz
        fills.append(FillRow(oid, coid, price, qty, ts))
    return orders, updates, fills


def pack_lane_state(
    *, next_oid: int, next_handle: int, free_handles, next_slot: int,
    free_slots, symbols, owners, orders, auction_mode: bool,
) -> bytes:
    """The adopt()/dump_state() blob (version 1).

    symbols: [(slot, live, symbol_str)]; owners: [(client_id, owner)];
    orders: [(handle, oid_num, client_id, symbol, side, otype, price_q4,
    quantity, remaining, status)]. Free lists keep their LIFO stack order —
    future handle/slot assignment depends on it."""
    out = bytearray(struct.pack("<IqI", 1, next_oid, next_handle & 0xFFFFFFFF))
    out += struct.pack("<I", len(free_handles))
    for h in free_handles:
        out += struct.pack("<i", h)
    out += struct.pack("<iI", next_slot, len(free_slots))
    for s in free_slots:
        out += struct.pack("<i", s)
    out += struct.pack("<I", len(symbols))
    for slot, live, sym in symbols:
        out += struct.pack("<iq", slot, live)
        _pack_str(out, sym)
    out += struct.pack("<I", len(owners))
    for cid, owner in owners:
        _pack_str(out, cid)
        out += struct.pack("<i", owner)
    out += struct.pack("<I", len(orders))
    for (handle, oid, cid, sym, side, otype, price, qty, rem, st) in orders:
        out += struct.pack("<iq", handle, oid)
        _pack_str(out, cid)
        _pack_str(out, sym)
        out += struct.pack("<iiiqqi", side, otype, price, qty, rem, st)
    out += struct.pack("<i", 1 if auction_mode else 0)
    return bytes(out)


def parse_lane_state(buf: bytes) -> dict:
    """Inverse of pack_lane_state (reads dump_state output)."""
    r = _Rd(buf)
    version = r.u32()
    if version != 1:
        raise ValueError(f"lane state blob version {version}")
    out = {"next_oid": r.i64(), "next_handle": r.i32()}
    out["free_handles"] = [r.i32() for _ in range(r.u32())]
    out["next_slot"] = r.i32()
    out["free_slots"] = [r.i32() for _ in range(r.u32())]
    out["symbols"] = [
        (r.i32(), r.i64(), r.s().decode()) for _ in range(r.u32())
    ]
    out["owners"] = [(r.s().decode(), r.i32()) for _ in range(r.u32())]
    out["orders"] = [
        (r.i32(), r.i64(), r.s().decode(), r.s().decode(), r.i32(),
         r.i32(), r.i32(), r.i64(), r.i64(), r.i32())
        for _ in range(r.u32())
    ]
    out["auction_mode"] = r.i32() != 0
    return out


class NativeLanes:
    """ctypes driver of the C++ lane engine (one per EngineRunner).

    Protocol per dispatch (caller holds the runner's dispatch lock):
    build() -> wave() x n_waves (device_put + step each) -> decode_wave()
    per readback (FIFO over staged dispatches) -> finish() -> take().
    """

    def __init__(self, num_symbols: int, batch: int, fill_inline: int,
                 max_fills: int):
        import numpy as np

        self._np = np
        self._lib = _load()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        self._h = self._lib.me_lanes_create(num_symbols, batch, fill_inline,
                                            max_fills)
        if not self._h:
            raise RuntimeError("me_lanes_create failed")
        self.S, self.B, self.L = num_symbols, batch, fill_inline
        self.max_fills = max_fills

    def destroy(self) -> None:
        if self._h:
            self._lib.me_lanes_destroy(self._h)
            self._h = None

    @staticmethod
    def _i32p(arr):
        return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))

    def build(self, recs, n: int, build_ou: bool, build_md: bool):
        """Stage one dispatch from `n` MeGwOp records ((MeGwOp * k) array).

        Returns (shape, n_waves, n_lanes, n_ops, wave_k, wave_n) or raises
        on a malformed record / allocator exhaustion (the caller fails the
        batch; eager registrations were already rolled back natively).
        wave_n (real ops per wave) sizes the megadispatch compacted-result
        bucket — the host knows every wave's op count, so the compacted
        readback can never truncate."""
        max_waves = n // self.B + 2
        flags = (ctypes.c_int32 * 4)()
        wave_n = (ctypes.c_int32 * max_waves)()
        wave_k = (ctypes.c_int32 * max_waves)()
        rc = self._lib.me_lanes_build(
            self._h, recs, n, 1 if build_ou else 0, 1 if build_md else 0,
            flags, wave_n, wave_k, max_waves,
        )
        if rc < 0:
            raise RuntimeError("me_lanes_build failed (malformed record or "
                               "allocator exhaustion)")
        shape, n_waves, n_lanes, n_ops = (flags[0], flags[1], flags[2],
                                          flags[3])
        return (shape, n_waves, n_lanes, n_ops, list(wave_k[:n_waves]),
                list(wave_n[:n_waves]))

    def wave(self, w: int, shape: int, k: int):
        """Materialize wave `w`'s lane buffer: sparse -> [K, 9] int32,
        dense -> [S, B, 7] int32 (ready for device_put)."""
        np = self._np
        if shape == 0:
            arr = np.empty((k, 9), dtype=np.int32)
        else:
            arr = np.empty((self.S, self.B, 7), dtype=np.int32)
        if self._lib.me_lanes_wave(self._h, w, self._i32p(arr)) != 0:
            raise RuntimeError("me_lanes_wave failed")
        return arr

    def decode_wave(self, small, fills_fetch) -> int:
        """Decode the OLDEST staged dispatch's next wave from its packed
        small-vector readback (int32 numpy). `fills_fetch()` lazily
        fetches the full [5, max_fills] buffer when the fill log exceeded
        the inline segment. Returns the wave's fill count."""
        np = self._np
        small = np.ascontiguousarray(small, dtype=np.int32)
        rc = self._lib.me_lanes_decode_wave(
            self._h, self._i32p(small), small.size, None, 0)
        if rc == -2:
            fills = np.ascontiguousarray(fills_fetch(), dtype=np.int32)
            rc = self._lib.me_lanes_decode_wave(
                self._h, self._i32p(small), small.size, self._i32p(fills),
                fills.size)
        if rc < 0:
            raise RuntimeError("me_lanes_decode_wave failed")
        return int(rc)

    def wave_mega(self, w0: int, m: int):
        """ONE stacked [m, S, B, 7] megadispatch buffer covering waves
        [w0, w0+m) of the just-built dispatch (dense only) — ready for
        kernel.engine_step_mega."""
        np = self._np
        arr = np.empty((m, self.S, self.B, 7), dtype=np.int32)
        if self._lib.me_lanes_wave_mega(self._h, w0, m,
                                        self._i32p(arr)) != 0:
            raise RuntimeError("me_lanes_wave_mega failed")
        return arr

    def decode_mega(self, m: int, rcap: int, lo: int, small,
                    fills_fetch) -> tuple[int, bool]:
        """Decode m stacked waves of the OLDEST staged dispatch from one
        megadispatch readback (kernel.MegaStepOutput.small layout; `lo` =
        mega_fill_inline rows per wave). `fills_fetch()` lazily fetches
        the full [m, 5, max_fills] buffer when some wave's fill log
        exceeded its inline segment. Returns (total fill count,
        fetched_full)."""
        np = self._np
        small = np.ascontiguousarray(small, dtype=np.int32)
        rc = self._lib.me_lanes_decode_mega(
            self._h, self._i32p(small), small.size, m, rcap, lo, None, 0)
        fetched = False
        if rc == -2:
            fills = np.ascontiguousarray(fills_fetch(), dtype=np.int32)
            fetched = True
            rc = self._lib.me_lanes_decode_mega(
                self._h, self._i32p(small), small.size, m, rcap, lo,
                self._i32p(fills), fills.size)
        if rc < 0:
            raise RuntimeError("me_lanes_decode_mega failed")
        return int(rc), fetched

    def finish_take(self) -> tuple[bytes, bytes, bytes]:
        """Assemble + copy out the oldest dispatch's (completions, storage,
        aux) buffers; pops it from the staged FIFO."""
        lens = [ctypes.c_longlong() for _ in range(3)]
        if self._lib.me_lanes_finish(self._h, *[ctypes.byref(v)
                                                for v in lens]) != 0:
            raise RuntimeError("me_lanes_finish failed")
        bufs = [(ctypes.c_uint8 * v.value)() for v in lens]
        if self._lib.me_lanes_take(self._h, *bufs) != 0:
            raise RuntimeError("me_lanes_take failed")
        return tuple(bytes(b) for b in bufs)

    def abort(self, newest: bool) -> None:
        self._lib.me_lanes_abort(self._h, 1 if newest else 0)

    def get_order(self, handle: int):
        """(oid_num, side, otype, price_q4, status, quantity, remaining,
        symbol, client_id) or None."""
        oid = ctypes.c_longlong()
        i32s = (ctypes.c_int32 * 5)()
        i64s = (ctypes.c_longlong * 2)()
        sym = ctypes.create_string_buffer(68)
        cid = ctypes.create_string_buffer(260)
        sym_len = ctypes.c_int32()
        cid_len = ctypes.c_int32()
        rc = self._lib.me_lanes_get_order(
            self._h, handle, ctypes.byref(oid), i32s, i64s, sym,
            ctypes.byref(sym_len), cid, ctypes.byref(cid_len))
        if not rc:
            return None
        return (oid.value, i32s[0], i32s[1], i32s[2], i32s[3],
                i64s[0], i64s[1], sym.raw[:sym_len.value].decode(),
                cid.raw[:cid_len.value].decode())

    def lookup(self, order_id: str) -> int:
        b = order_id.encode()
        return int(self._lib.me_lanes_lookup(self._h, b, len(b)))

    def adjust(self, handle: int, remaining: int, status: int) -> bool:
        return bool(self._lib.me_lanes_adjust(self._h, handle, remaining,
                                              status))

    def evict(self, handle: int) -> int | None:
        """Evict a live order; returns the released slot (or None)."""
        released = ctypes.c_int32(-1)
        if not self._lib.me_lanes_evict(self._h, handle,
                                        ctypes.byref(released)):
            return None
        return released.value if released.value >= 0 else None

    def set_auction_mode(self, value: bool) -> None:
        self._lib.me_lanes_set_auction_mode(self._h, 1 if value else 0)

    def set_oid_stride(self, stride: int) -> None:
        """Partitioned serving: this lane allocates every `stride`-th OID
        (adopt()/the runner's seeding put next_oid on the lane's residue
        class; the stride keeps it there)."""
        self._lib.me_lanes_set_oid_stride(self._h, stride)

    def adopt(self, blob: bytes) -> None:
        buf = (ctypes.c_uint8 * len(blob)).from_buffer_copy(blob)
        rc = self._lib.me_lanes_adopt(self._h, buf, len(blob))
        if rc != 0:
            raise RuntimeError(
                "me_lanes_adopt failed"
                + (" (dispatches still staged)" if rc == -2 else ""))

    def dump_state(self) -> bytes:
        n = self._lib.me_lanes_dump_state(self._h, None, 0)
        buf = (ctypes.c_uint8 * n)()
        if self._lib.me_lanes_dump_state(self._h, buf, n) != n:
            raise RuntimeError("me_lanes_dump_state failed")
        return bytes(buf)

    def stats(self) -> dict:
        live = ctypes.c_longlong()
        next_oid = ctypes.c_longlong()
        staged = ctypes.c_longlong()
        self._lib.me_lanes_stats(self._h, ctypes.byref(live),
                                 ctypes.byref(next_oid), ctypes.byref(staged))
        return {"live_orders": live.value, "next_oid": next_oid.value,
                "staged_dispatches": staged.value}


class LaneRing:
    """Bounded MPSC MeGwOp record ring (native/me_lanes.cpp GwRing): the
    grpcio edge's record dispatcher pushes wide records here and the drain
    loop pops RAW batches — the same batching-window semantics as the
    gateway's internal ring, without per-record Python decode."""

    def __init__(self, capacity: int = 1 << 16):
        self._lib = _load()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        self._h = self._lib.me_gwring_create(capacity)
        if not self._h:
            raise RuntimeError("me_gwring_create failed")
        self._buf = None

    def push(self, rec: MeGwOp) -> bool:
        if self._h is None:
            return False
        return bool(self._lib.me_gwring_push(self._h, ctypes.byref(rec)))

    def push_n(self, recs, n: int) -> bool:
        """All-or-nothing bulk push ((MeGwOp * k) array, first n records)
        under one ring lock acquisition — the batch edge's enqueue. False
        means the ring could not hold the WHOLE batch (nothing entered)."""
        if self._h is None:
            return False
        return bool(self._lib.me_gwring_push_n(self._h, recs, n))

    def pop_batch_raw(self, max_ops: int, window_us: int,
                      first_wait_us: int = -1):
        """(records_array, n): n == 0 on first-wait timeout, None when
        closed+empty. The array is reused across pops (single consumer)."""
        if self._h is None:
            return None, 0
        buf = self._buf
        if buf is None or len(buf) < max_ops:
            buf = self._buf = (MeGwOp * max_ops)()
        n = self._lib.me_gwring_pop_batch(self._h, buf, max_ops, window_us,
                                          first_wait_us)
        if n < 0:
            return None, 0
        return buf, n

    def close(self) -> None:
        if self._h is not None:
            self._lib.me_gwring_close(self._h)

    def destroy(self) -> None:
        if self._h:
            self._lib.me_gwring_destroy(self._h)
            self._h = None

    @property
    def dropped(self) -> int:
        return 0 if self._h is None else self._lib.me_gwring_dropped(self._h)


class ShmRing:
    """The shared-memory ingress segment (native/me_shmring.cpp): a
    file-backed ring of 384-byte op-records with per-slot commit words, a
    futex doorbell, and a response ring of MeShmResp records.

    Server: ShmRing(path, create=True) + poll()/respond()/stats();
    client: ShmRing(path) + push_payload()/resp_poll(). The request ring
    is MULTI-PRODUCER (v2): any number of attached processes may
    claim/commit concurrently; register_writer() leases a private
    response lane (ids 1..15) so each client sees exactly its own acks,
    while an unregistered handle rides the anonymous lane 0 (the v1
    single-client behavior). The poller stays the single consumer and
    the server the single response publisher. Crash-safety (claim-stamp
    attribution, pid-leased torn recovery) lives in the C++ layer — see
    the me_shmring.cpp header comment."""

    def __init__(self, path: str, create: bool = False,
                 slots: int = 4096, resp_slots: int = 8192):
        self._lib = _load()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        if create:
            self._h = self._lib.me_shmring_create(path.encode(), slots,
                                                  resp_slots)
        else:
            self._h = self._lib.me_shmring_attach(path.encode())
        if not self._h:
            raise RuntimeError(
                f"me_shmring_{'create' if create else 'attach'} failed "
                f"for {path} (caps must be powers of two; attach needs a "
                f"live server segment)")
        self.path = path
        self.owner = create
        self._buf = None
        self._seqs = None
        self._resp_buf = None

    # -- writer (client process) ------------------------------------------

    def register_writer(self) -> int:
        """Lease a writer lane (ids 1..15): claims stamped under this
        registration are recovered only once this process is DEAD (the
        poller checks the registry pid), and responses to its records
        land on its private sub-ring. Returns the writer id; falls back
        to the anonymous lane 0 (deadline-only recovery, shared lane)
        when every slot is held by a live registrant."""
        wid = int(self._lib.me_shmring_register(self._h))
        return max(wid, 0)

    @property
    def writer_id(self) -> int:
        return int(self._lib.me_shmring_writer_id(self._h))

    def writer_count(self) -> int:
        """Live registered writers (the me_ingress_writers gauge)."""
        return int(self._lib.me_shmring_writer_count(self._h))

    def push_payload(self, body: bytes, n: int) -> int:
        """Copy-in write of a packed record run (no magic): claim n
        slots, write, commit each, ring the doorbell. Returns the base
        ring sequence; -1 full (caller backs off), -2 server shutdown."""
        if n <= 0:
            return -1
        return int(self._lib.me_shmring_push_n(self._h, body, n))

    def claim(self, n: int) -> int:
        return int(self._lib.me_shmring_claim(self._h, n))

    def write_slot(self, seq: int, record: bytes) -> None:
        """Write one claimed slot's bytes WITHOUT committing (the
        kill-fuzz writer splits write and commit so SIGKILL can land
        between them)."""
        p = self._lib.me_shmring_slot(self._h, seq)
        ctypes.memmove(p, record, len(record))

    def commit(self, seq: int) -> None:
        self._lib.me_shmring_commit(self._h, seq)

    def wake(self) -> None:
        self._lib.me_shmring_wake(self._h)

    # -- poller (server thread) -------------------------------------------

    def poll(self, max_records: int, wait_us: int, torn_wait_us: int,
             window_us: int = 2000):
        """(records_bytes, seqs_list, torn) — records_bytes is the packed
        run of admitted records (length n*384, decode with
        np.frombuffer(OPREC_DTYPE)); seqs_list maps each record to its
        ring sequence (torn recovery makes runs non-contiguous). Waits
        up to wait_us for the first record, then collects for up to
        window_us more (the batching-window semantics every ring pop in
        this repo uses). n == 0 on timeout; records_bytes is None when
        the segment shut down."""
        import numpy as np

        buf = self._buf
        if buf is None or len(buf) < max_records * 384:
            buf = self._buf = np.zeros(max_records * 384, dtype=np.uint8)
            self._seqs = (ctypes.c_longlong * max_records)()
        torn = ctypes.c_longlong()
        n = self._lib.me_shmring_poll(
            self._h, buf.ctypes.data_as(ctypes.c_void_p), self._seqs,
            max_records, wait_us, window_us, torn_wait_us,
            ctypes.byref(torn))
        if n == -2:
            return None, [], int(torn.value)
        if n <= 0:
            return b"", [], int(torn.value)
        return (buf[:n * 384].tobytes(), list(self._seqs[:n]),
                int(torn.value))

    def respond(self, resps) -> int:
        """Publish a (MeShmResp * k) array's first len slice (or a list
        of MeShmResp); returns the number written (the rest counted as
        resp_dropped — the server never blocks on a slow client)."""
        if isinstance(resps, list):
            arr = (MeShmResp * max(1, len(resps)))(*resps)
            k = len(resps)
        else:
            arr, k = resps, len(resps)
        if k == 0:
            return 0
        return int(self._lib.me_shmring_respond_n(self._h, arr, k))

    def respond_payload(self, buf: bytes, n: int) -> int:
        """Publish n packed MeShmResp records from raw bytes (the
        poller builds them as ONE numpy SHM_RESP_DTYPE array — no
        per-op ctypes objects on the response path)."""
        if n == 0:
            return 0
        arr = ctypes.cast(ctypes.c_char_p(buf),
                          ctypes.POINTER(MeShmResp))
        return int(self._lib.me_shmring_respond_n(self._h, arr, n))

    def resp_poll_raw(self, max_records: int, wait_us: int):
        """Client fast path: up to max_records responses as RAW bytes
        (n * 48, decode vectorized with oprec.SHM_RESP_DTYPE), or None
        when the server shut down and the ring is drained."""
        buf = self._resp_buf
        if buf is None or len(buf) < max_records:
            buf = self._resp_buf = (MeShmResp * max_records)()
        n = self._lib.me_shmring_resp_poll(self._h, buf, max_records,
                                           wait_us)
        if n == -2:
            return None
        if n <= 0:
            return b""
        return ctypes.string_at(buf, n * ctypes.sizeof(MeShmResp))

    def resp_poll(self, max_records: int, wait_us: int):
        """Client: list of MeShmResp copies (empty on timeout), or None
        when the server shut down and the ring is drained."""
        buf = self._resp_buf
        if buf is None or len(buf) < max_records:
            buf = self._resp_buf = (MeShmResp * max_records)()
        n = self._lib.me_shmring_resp_poll(self._h, buf, max_records,
                                           wait_us)
        if n == -2:
            return None
        out = []
        for i in range(max(0, n)):
            r = buf[i]
            out.append((int(r.seq), bool(r.ok), int(r.kind),
                        int(r.reason),
                        bytes(r.order_id[:r.oid_len]).decode(
                            errors="replace"),
                        int(r.remaining)))
        return out

    def stats(self) -> dict:
        depth = ctypes.c_longlong()
        torn = ctypes.c_longlong()
        dropped = ctypes.c_longlong()
        wakes = ctypes.c_longlong()
        self._lib.me_shmring_stats(self._h, ctypes.byref(depth),
                                   ctypes.byref(torn), ctypes.byref(dropped),
                                   ctypes.byref(wakes))
        return {"depth": depth.value, "torn_recovered": torn.value,
                "resp_dropped": dropped.value,
                "doorbell_wakes": wakes.value}

    def shutdown(self) -> None:
        """Server: latch the segment closed (writers/readers unblock)."""
        if self._h:
            self._lib.me_shmring_shutdown(self._h)

    def close(self) -> None:
        if self._h:
            self._lib.me_shmring_close(self._h)
            self._h = None
        if self.owner:
            try:
                os.unlink(self.path)
            except OSError:
                pass
