"""ctypes bindings for the C++ runtime layer (native/me_native.cpp).

The reference is an all-C++ gateway; this package is where the new
framework's host runtime stays native: Q4 price arithmetic, the MPSC
op ring behind the batch dispatcher, and the async SQLite sink. Each
binding has a pure-Python twin (domain/price.py, server/dispatcher.py,
storage/async_sink.py) — the native path is selected when the library is
present, and parity between the two is enforced by tests/test_native.py.

`ensure_built()` compiles the library on demand (g++ + system libsqlite3;
nothing to pip-install). `available()` gates call sites.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading

_PKG_DIR = os.path.dirname(__file__)
_LIB_PATH = os.path.join(_PKG_DIR, "libme_native.so")
_SRC_DIR = os.path.normpath(os.path.join(_PKG_DIR, "..", "..", "native"))
_SRC = os.path.join(_SRC_DIR, "me_native.cpp")

_lib = None
_lib_lock = threading.Lock()

# me_validate_submit codes -> the service's reject messages
# (reference matching_engine_service.cpp:66-83 wording preserved upstream).
VALIDATE_MESSAGES = {
    1: "symbol is required",
    2: "quantity must be positive",
    3: "price must be positive for LIMIT orders",
    4: "scale out of range [0, 18]",
    5: "price overflows the engine's Q4 range",
    6: "quantity exceeds the engine maximum",
    7: "side must be BUY or SELL",
    8: "order_type must be LIMIT or MARKET",
    9: "symbol too long",
    10: "client_id too long",
}


class MeOp(ctypes.Structure):
    _fields_ = [
        ("tag", ctypes.c_uint64),
        ("sym", ctypes.c_int32),
        ("op", ctypes.c_int32),
        ("side", ctypes.c_int32),
        ("otype", ctypes.c_int32),
        ("price", ctypes.c_int32),
        ("qty", ctypes.c_int32),
        ("oid", ctypes.c_int32),
        ("pad", ctypes.c_int32),
    ]


def ensure_built(force: bool = False) -> bool:
    """Build libme_native.so if missing or stale. Returns availability."""
    if os.path.exists(_LIB_PATH) and not force:
        if not os.path.exists(_SRC) or (
            os.path.getmtime(_LIB_PATH) >= os.path.getmtime(_SRC)
        ):
            return True
    if not os.path.exists(_SRC):
        return os.path.exists(_LIB_PATH)
    try:
        subprocess.run(
            ["make", "-s"], cwd=_SRC_DIR, check=True, capture_output=True
        )
        return True
    except (subprocess.CalledProcessError, FileNotFoundError) as e:
        out = getattr(e, "stderr", b"") or b""
        print(f"[native] build failed: {out.decode(errors='replace')[-500:]}")
        return os.path.exists(_LIB_PATH)


def _load():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not ensure_built():
            return None
        lib = ctypes.CDLL(_LIB_PATH)
        lib.me_normalize_to_q4.argtypes = [
            ctypes.c_longlong, ctypes.c_int, ctypes.POINTER(ctypes.c_longlong)
        ]
        lib.me_normalize_to_q4.restype = ctypes.c_int
        lib.me_validate_submit.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_longlong, ctypes.c_int,
            ctypes.c_int, ctypes.c_longlong, ctypes.c_int,
            ctypes.c_longlong, ctypes.c_longlong, ctypes.c_int, ctypes.c_int,
        ]
        lib.me_validate_submit.restype = ctypes.c_int

        lib.me_ring_create.argtypes = [ctypes.c_uint32]
        lib.me_ring_create.restype = ctypes.c_void_p
        lib.me_ring_destroy.argtypes = [ctypes.c_void_p]
        lib.me_ring_push.argtypes = [ctypes.c_void_p, ctypes.POINTER(MeOp)]
        lib.me_ring_push.restype = ctypes.c_int
        lib.me_ring_pop_batch.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(MeOp), ctypes.c_uint32,
            ctypes.c_uint64,
        ]
        lib.me_ring_pop_batch.restype = ctypes.c_int
        lib.me_ring_pop_batch_timed.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(MeOp), ctypes.c_uint32,
            ctypes.c_uint64, ctypes.c_int64,
        ]
        lib.me_ring_pop_batch_timed.restype = ctypes.c_int
        lib.me_ring_close.argtypes = [ctypes.c_void_p]
        lib.me_ring_dropped.argtypes = [ctypes.c_void_p]
        lib.me_ring_dropped.restype = ctypes.c_uint64
        lib.me_ring_size.argtypes = [ctypes.c_void_p]
        lib.me_ring_size.restype = ctypes.c_uint64

        lib.me_sink_open.argtypes = [ctypes.c_char_p, ctypes.c_uint32]
        lib.me_sink_open.restype = ctypes.c_void_p
        lib.me_sink_submit.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int
        ]
        lib.me_sink_submit.restype = ctypes.c_int
        lib.me_sink_flush.argtypes = [ctypes.c_void_p]
        lib.me_sink_stats.argtypes = [ctypes.c_void_p] + [
            ctypes.POINTER(ctypes.c_uint64)
        ] * 4
        lib.me_sink_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    try:
        return _load() is not None
    except OSError:
        return False


# -- domain -----------------------------------------------------------------

def normalize_to_q4(price: int, raw_scale: int) -> int:
    """Native twin of domain.price.normalize_to_q4 (same raise behavior)."""
    from matching_engine_tpu.domain.price import PriceError

    lib = _load()
    out = ctypes.c_longlong()
    rc = lib.me_normalize_to_q4(price, raw_scale, ctypes.byref(out))
    if rc == 1:
        raise PriceError(f"scale {raw_scale} out of range [0, 18]")
    if rc == 2:
        raise PriceError(
            f"price {price} at scale {raw_scale} overflows int64 when "
            f"normalized to Q4"
        )
    return out.value


def validate_submit_code(
    symbol_len: int, client_id_len: int, quantity: int, side: int,
    order_type: int, price: int, scale: int,
) -> int:
    """0 = valid; else a VALIDATE_MESSAGES key. Bounds come from the domain
    constants so native and Python validation can never drift."""
    from matching_engine_tpu.domain.order import (
        MAX_CLIENT_ID_BYTES,
        MAX_QUANTITY,
        MAX_SYMBOL_BYTES,
    )
    from matching_engine_tpu.domain.price import MAX_DEVICE_PRICE_Q4

    return _load().me_validate_submit(
        symbol_len, client_id_len, quantity, side, order_type, price, scale,
        MAX_DEVICE_PRICE_Q4, MAX_QUANTITY, MAX_SYMBOL_BYTES,
        MAX_CLIENT_ID_BYTES,
    )


# -- ring -------------------------------------------------------------------

class NativeRing:
    """Bounded MPSC op ring; the batching window runs in C++ off the GIL."""

    def __init__(self, capacity: int = 1 << 16):
        self._lib = _load()
        self._h = self._lib.me_ring_create(capacity)
        if not self._h:
            raise RuntimeError("me_ring_create failed")
        self._buf = None  # reused pop buffer (single consumer)

    def push(self, tag: int, sym: int, op: int, side: int, otype: int,
             price: int, qty: int, oid: int) -> bool:
        if self._h is None:  # destroyed ring: behave as closed, never segv
            return False
        rec = MeOp(tag=tag, sym=sym, op=op, side=side, otype=otype,
                   price=price, qty=qty, oid=oid, pad=0)
        return bool(self._lib.me_ring_push(self._h, ctypes.byref(rec)))

    def pop_batch(self, max_ops: int, window_us: int,
                  first_wait_us: int = -1):
        """Blocks for the first op (bounded when first_wait_us >= 0), then
        drains up to (max_ops, window_us). Returns a list of MeOp field
        tuples, [] on first-wait timeout, or None when closed+empty.

        The output buffer is allocated once and reused — the ring has a
        single consumer, and max_ops can be thousands of 40-byte records per
        ~2ms drain window."""
        if self._h is None:
            return None
        buf = self._buf
        if buf is None or len(buf) < max_ops:
            buf = self._buf = (MeOp * max_ops)()
        n = self._lib.me_ring_pop_batch_timed(self._h, buf, max_ops,
                                              window_us, first_wait_us)
        if n < 0:
            return None
        return [
            (r.tag, r.sym, r.op, r.side, r.otype, r.price, r.qty, r.oid)
            for r in buf[:n]
        ]

    def close(self) -> None:
        if self._h is not None:
            self._lib.me_ring_close(self._h)

    def destroy(self) -> None:
        if self._h:
            self._lib.me_ring_destroy(self._h)
            self._h = None

    @property
    def dropped(self) -> int:
        return 0 if self._h is None else self._lib.me_ring_dropped(self._h)

    def __len__(self) -> int:
        return 0 if self._h is None else self._lib.me_ring_size(self._h)


# -- gateway ----------------------------------------------------------------

_GW_LIB_PATH = os.path.join(_PKG_DIR, "libme_gateway.so")
_CLIENT_PATH = os.path.join(_PKG_DIR, "me_client")
_gw_lib = None

# Python mirror of MeGwOp (native/me_gateway.cpp) — keep layouts identical.
# Strings are length-prefixed (embedded NULs round-trip like the grpcio edge).
class MeGwOp(ctypes.Structure):
    _fields_ = [
        ("tag", ctypes.c_uint64),
        ("op", ctypes.c_int32),        # 1 submit / 2 cancel
        ("side", ctypes.c_int32),
        ("otype", ctypes.c_int32),
        ("price_q4", ctypes.c_int32),
        ("quantity", ctypes.c_int64),
        ("symbol_len", ctypes.c_int32),
        ("client_id_len", ctypes.c_int32),
        ("order_id_len", ctypes.c_int32),
        ("symbol", ctypes.c_char * 68),
        ("client_id", ctypes.c_char * 260),
        ("order_id", ctypes.c_char * 36),
    ]


GW_CALLBACK = ctypes.CFUNCTYPE(
    None, ctypes.c_uint64, ctypes.c_int, ctypes.POINTER(ctypes.c_uint8),
    ctypes.c_uint64,
)

# Forwarded-method ids (me_gateway.cpp Method enum).
(GW_SUBMIT, GW_CANCEL, GW_BOOK, GW_METRICS, GW_STREAM_MD, GW_STREAM_OU,
 GW_AUCTION) = range(1, 8)


def _load_gateway():
    global _gw_lib
    with _lib_lock:
        if _gw_lib is not None:
            return _gw_lib
        if not ensure_built():
            return None
        if not os.path.exists(_GW_LIB_PATH):
            return None
        lib = ctypes.CDLL(_GW_LIB_PATH)
        lib.me_gateway_create.argtypes = [
            ctypes.c_char_p, ctypes.c_uint32, ctypes.c_longlong,
            ctypes.c_longlong, ctypes.c_int, ctypes.c_int,
        ]
        lib.me_gateway_create.restype = ctypes.c_void_p
        lib.me_gateway_start.argtypes = [ctypes.c_void_p]
        lib.me_gateway_start.restype = ctypes.c_int
        lib.me_gateway_port.argtypes = [ctypes.c_void_p]
        lib.me_gateway_port.restype = ctypes.c_int
        lib.me_gateway_set_callback.argtypes = [ctypes.c_void_p, GW_CALLBACK]
        lib.me_gw_pop_batch_timed.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(MeGwOp), ctypes.c_uint32,
            ctypes.c_uint64, ctypes.c_int64,
        ]
        lib.me_gw_pop_batch_timed.restype = ctypes.c_int
        lib.me_gw_pop_batch.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(MeGwOp), ctypes.c_uint32,
            ctypes.c_uint64,
        ]
        lib.me_gw_pop_batch.restype = ctypes.c_int
        lib.me_gateway_complete_submit.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int, ctypes.c_char_p,
            ctypes.c_char_p,
        ]
        lib.me_gateway_complete_cancel.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int, ctypes.c_char_p,
            ctypes.c_char_p,
        ]
        lib.me_gateway_complete_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
        ]
        lib.me_gateway_complete_amend.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int, ctypes.c_char_p,
            ctypes.c_longlong, ctypes.c_char_p,
        ]
        lib.me_gateway_respond.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p,
            ctypes.c_uint64, ctypes.c_int, ctypes.c_int, ctypes.c_char_p,
        ]
        lib.me_gateway_respond.restype = ctypes.c_int
        lib.me_gateway_stream_alive.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.me_gateway_stream_alive.restype = ctypes.c_int
        lib.me_gateway_stats.argtypes = [ctypes.c_void_p] + [
            ctypes.POINTER(ctypes.c_uint64)
        ] * 3
        lib.me_gateway_shutdown.argtypes = [ctypes.c_void_p]
        lib.me_gateway_destroy.argtypes = [ctypes.c_void_p]
        _gw_lib = lib
        return _gw_lib


def gateway_available() -> bool:
    try:
        return _load_gateway() is not None
    except OSError:
        return False


def client_binary() -> str | None:
    """Path to the native CLI client, if built."""
    ensure_built()
    return _CLIENT_PATH if os.path.exists(_CLIENT_PATH) else None


class NativeGateway:
    """The C++ gRPC serving edge (native/me_gateway.cpp).

    Hot-path ops (submit/cancel) surface through `pop_batch` as wide
    records and are answered with `complete_*`; forwarded methods
    (book/metrics/streams) arrive via the registered callback and are
    answered with `respond`.
    """

    def __init__(self, addr: str = "0.0.0.0:0", ring_capacity: int = 1 << 15):
        from matching_engine_tpu.domain.order import (
            MAX_CLIENT_ID_BYTES,
            MAX_QUANTITY,
            MAX_SYMBOL_BYTES,
        )
        from matching_engine_tpu.domain.price import MAX_DEVICE_PRICE_Q4

        lib = _load_gateway()
        if lib is None:
            raise RuntimeError("native gateway library unavailable")
        self._lib = lib
        self._h = lib.me_gateway_create(
            addr.encode(), ring_capacity, MAX_DEVICE_PRICE_Q4, MAX_QUANTITY,
            MAX_SYMBOL_BYTES, MAX_CLIENT_ID_BYTES,
        )
        if not self._h:
            raise RuntimeError("me_gateway_create failed")
        self._cb_ref = None  # keep the CFUNCTYPE object alive
        self._buf = None
        self.port = -1

    def start(self) -> int:
        port = self._lib.me_gateway_start(self._h)
        if port < 0:
            raise RuntimeError("native gateway failed to bind")
        self.port = port
        return port

    def set_callback(self, fn) -> None:
        """fn(tag: int, method: int, payload: bytes); runs on a C++
        connection thread (ctypes acquires the GIL) — must not block."""

        def _trampoline(tag, method, data, length):
            try:
                payload = ctypes.string_at(data, length) if length else b""
                fn(tag, method, payload)
            except Exception as e:  # noqa: BLE001 — never unwind into C++
                print(f"[gateway] callback error: {type(e).__name__}: {e}")

        self._cb_ref = GW_CALLBACK(_trampoline)
        self._lib.me_gateway_set_callback(self._h, self._cb_ref)

    def pop_batch(self, max_ops: int, window_us: int,
                  first_wait_us: int = -1):
        """Blocks for the first op (bounded when first_wait_us >= 0),
        drains to (max_ops, window_us). Returns a list of (tag, op, side,
        otype, price_q4, quantity, symbol, client_id, order_id), [] on
        first-wait timeout, or None when shut down."""
        if self._h is None:
            return None
        buf = self._buf
        if buf is None or len(buf) < max_ops:
            buf = self._buf = (MeGwOp * max_ops)()
        n = self._lib.me_gw_pop_batch_timed(self._h, buf, max_ops,
                                            window_us, first_wait_us)
        if n < 0:
            return None
        out = []
        for r in buf[:n]:
            try:
                out.append(
                    (r.tag, r.op, r.side, r.otype, r.price_q4, r.quantity,
                     bytes(r.symbol[:r.symbol_len]).decode(),
                     bytes(r.client_id[:r.client_id_len]).decode(),
                     bytes(r.order_id[:r.order_id_len]).decode())
                )
            except UnicodeDecodeError:
                # Per-record failure: a hostile payload surviving the C++
                # parse must poison only ITS op, never the batch — the
                # bridge rejects string-fields-None records individually.
                out.append((r.tag, r.op, r.side, r.otype, r.price_q4,
                            r.quantity, None, None, None))
        return out

    def complete_submit(self, tag: int, success: bool, order_id: str,
                        error: str = "") -> None:
        if self._h is None:
            return
        self._lib.me_gateway_complete_submit(
            self._h, tag, 1 if success else 0, order_id.encode(),
            error.encode(),
        )

    def complete_cancel(self, tag: int, success: bool, order_id: str,
                        error: str = "") -> None:
        if self._h is None:
            return
        self._lib.me_gateway_complete_cancel(
            self._h, tag, 1 if success else 0, order_id.encode(),
            error.encode(),
        )

    def complete_amend(self, tag: int, success: bool, order_id: str,
                       remaining: int = 0, error: str = "") -> None:
        if self._h is None:
            return
        self._lib.me_gateway_complete_amend(
            self._h, tag, 1 if success else 0, order_id.encode(),
            remaining, error.encode(),
        )

    def complete_batch(
        self, items: list[tuple[int, int, bool, str, str]]
    ) -> None:
        """One ctypes crossing for a whole dispatch's completions.

        items: (tag, kind 0=submit/1=cancel, success, order_id, error).
        The C++ side groups by connection and writes each connection's
        response frames with a single locked send (me_gateway.cpp
        me_gateway_complete_batch — the wire format lives there).
        """
        if self._h is None or not items:
            return
        out = bytearray(struct.pack("<I", len(items)))
        for (tag, kind, success, order_id, error) in items:
            oid = order_id.encode()
            err = error.encode()
            out += struct.pack("<QBBH", tag, kind, 1 if success else 0,
                               len(oid))
            out += oid
            out += struct.pack("<H", len(err))
            out += err
        buf = bytes(out)
        self._lib.me_gateway_complete_batch(self._h, buf, len(buf))

    def respond(self, tag: int, msg: bytes | None, end_stream: bool,
                grpc_status: int = 0, grpc_message: str = "") -> bool:
        if self._h is None:
            return False
        return bool(self._lib.me_gateway_respond(
            self._h, tag, msg, len(msg) if msg else 0,
            1 if end_stream else 0, grpc_status, grpc_message.encode(),
        ))

    def stream_alive(self, tag: int) -> bool:
        if self._h is None:
            return False
        return bool(self._lib.me_gateway_stream_alive(self._h, tag))

    def stats(self) -> dict:
        if self._h is None:
            return {"requests": 0, "ring_rejects": 0, "conns": 0}
        vals = [ctypes.c_uint64() for _ in range(3)]
        self._lib.me_gateway_stats(self._h, *[ctypes.byref(v) for v in vals])
        return {
            "requests": vals[0].value,
            "ring_rejects": vals[1].value,
            "conns": vals[2].value,
        }

    def shutdown(self) -> None:
        if self._h is not None:
            self._lib.me_gateway_shutdown(self._h)

    def destroy(self) -> None:
        if self._h:
            self._lib.me_gateway_destroy(self._h)
            self._h = None


# -- sink -------------------------------------------------------------------

def _pack_str(out: bytearray, s: str) -> None:
    b = s.encode()
    out += struct.pack("<H", len(b))
    out += b


def pack_batch(orders, updates, fills) -> bytes:
    """Serialize one dispatch for MeSink (format in me_native.cpp §3).

    orders: (order_id, client_id, symbol, side, collapsed_otype,
             price|None, qty, remaining, status) — field 5 is the engine's
             collapsed (order_type, tif) lane code (proto.split_otype);
             MeSink splits it into the order_type column (wire 0/1) and
             the tif column, mirroring Storage.apply_batch;
    updates: (order_id, status, remaining); fills: FillRow.
    """
    out = bytearray()
    out += struct.pack("<I", len(orders))
    for (oid, cid, sym, side, otype, price, qty, remaining, status) in orders:
        _pack_str(out, oid)
        _pack_str(out, cid)
        _pack_str(out, sym)
        out += struct.pack(
            "<BBBqqqB", side, otype, 0 if price is None else 1,
            price or 0, qty, remaining, status,
        )
    out += struct.pack("<I", len(updates))
    for u in updates:
        # 3-tuple: status/remaining update. 4-tuple: amend — also moves
        # quantity (has_qty flag byte; MeSink binds the amend statement).
        _pack_str(out, u[0])
        if len(u) == 3:
            out += struct.pack("<BqBq", u[1], u[2], 0, 0)
        else:
            out += struct.pack("<BqBq", u[1], u[2], 1, u[3])
    out += struct.pack("<I", len(fills))
    for f in fills:
        _pack_str(out, f.order_id)
        _pack_str(out, f.counter_order_id)
        out += struct.pack("<qqq", f.price_q4, f.quantity, f.ts)
    return bytes(out)


class NativeStorageSink:
    """Drop-in for storage.AsyncStorageSink backed by the C++ worker.

    Row-for-row identical SQLite output (enforced by tests/test_native.py);
    serialization happens on the caller's thread, SQLite work on the C++
    thread — the GIL is held only while packing bytes.
    """

    def __init__(self, db_path: str, max_queue: int = 4096):
        d = os.path.dirname(db_path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._lib = _load()
        self._h = self._lib.me_sink_open(db_path.encode(), max_queue)
        if not self._h:
            raise RuntimeError(f"me_sink_open({db_path}) failed")
        self.dropped = 0

    def submit(self, orders=None, updates=None, fills=None, block=True) -> bool:
        if self._h is None:
            return False
        buf = pack_batch(orders or [], updates or [], fills or [])
        if len(buf) <= 12:  # three zero counts — nothing to write
            return True
        ok = bool(self._lib.me_sink_submit(
            self._h, buf, len(buf), 1 if block else 0
        ))
        if not ok:
            self.dropped += 1
        return ok

    def flush(self) -> None:
        if self._h is not None:
            self._lib.me_sink_flush(self._h)

    def stats(self) -> dict:
        vals = [ctypes.c_uint64() for _ in range(4)]
        if self._h is None:
            return {"batches": 0, "rows": 0, "dropped": 0, "errors": 0}
        self._lib.me_sink_stats(self._h, *[ctypes.byref(v) for v in vals])
        return {
            "batches": vals[0].value, "rows": vals[1].value,
            "dropped": vals[2].value, "errors": vals[3].value,
        }

    def close(self) -> None:
        if self._h:
            self._lib.me_sink_close(self._h)
            self._h = None
