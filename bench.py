"""Benchmark: sustained match-engine throughput on the attached accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no benchmark numbers (BASELINE.md — its matching core
is an empty file and its hot path is one SQLite INSERT under a global mutex),
so vs_baseline is measured against this repo's north-star target of 10M
orders/sec (BASELINE.json) rather than a reference figure.

Method: steady-state device throughput of the jit'd engine step — a realistic
mixed stream (limit adds that rest, crossing limits, markets, cancels) is
pre-built into [S, B] dispatches, then K steps run back-to-back with the book
donated in HBM (no host round-trip of book state), timed end to end with
block_until_ready. orders/sec counts real (non-padding) ops.
"""

from __future__ import annotations

import json
import time

import jax

from matching_engine_tpu.engine.book import EngineConfig, init_book
from matching_engine_tpu.engine.harness import build_batches, random_order_stream
from matching_engine_tpu.engine.kernel import engine_step

NORTH_STAR = 10_000_000  # orders/sec, BASELINE.json


def main() -> None:
    # North-star condition (BASELINE.json): 4k symbols. batch=32 amortizes the
    # per-step dispatch overhead over a longer in-kernel scan.
    cfg = EngineConfig(num_symbols=4096, capacity=128, batch=32, max_fills=1 << 17)
    n_orders_per_wave = cfg.num_symbols * cfg.batch

    # Build a handful of full dispatches; cycle them during the timed loop.
    # (Each wave is dense: every [S, B] slot is a real op.)  Count real ops
    # from the host-side batches BEFORE device_put: reading a device array
    # back (np.asarray) mid-bench collapses the axon tunnel's async dispatch
    # pipeline and slows every subsequent step by ~1000x.
    import numpy as np

    waves = []
    wave_ops = []
    for w in range(4):
        stream = random_order_stream(
            cfg.num_symbols, 4 * n_orders_per_wave, seed=w, cancel_p=0.10,
            market_p=0.15, price_base=9_950, price_levels=100, price_step=1,
            qty_max=100,
        )
        batches = build_batches(cfg, stream)
        # Keep only dense-enough leading dispatches.
        for b in batches[:2]:
            wave_ops.append(int(np.count_nonzero(np.asarray(b.op))))
            waves.append(jax.device_put(b))

    book = init_book(cfg)
    # Warmup: compile + one pass over every wave shape.
    book, out = engine_step(cfg, book, waves[0])
    jax.block_until_ready(out)

    # The tunneled device shows large run-to-run scheduling variance and a
    # slow first-window ramp; discard one warm-up window, then report the
    # median of the remaining fully-synced windows as the sustained figure.
    iters = 20
    real_ops = sum(wave_ops[i % len(waves)] for i in range(iters))
    rates = []
    for _ in range(5):
        t0 = time.perf_counter()
        for i in range(iters):
            book, out = engine_step(cfg, book, waves[i % len(waves)])
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        rates.append(real_ops / dt)
    post_warm = sorted(rates[1:])
    value = post_warm[len(post_warm) // 2]
    print(json.dumps({
        "metric": "match_throughput",
        "value": round(value, 1),
        "unit": "orders/sec",
        "vs_baseline": round(value / NORTH_STAR, 4),
    }))


if __name__ == "__main__":
    main()
