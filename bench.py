"""Benchmark: sustained match-engine throughput on the attached accelerator.

Prints exactly ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}
and always exits 0 — a wedged TPU tunnel must degrade the number, never the
driver run (round 1's bench died rc=1 on backend init and hung >9 min on a
rerun; this orchestrator is the fix).

Structure: this process never imports jax. The measurement runs in a child
(benchmarks/bench_child.py) whose wall-clock is bounded here:

  1. preflight + measure on the default backend (TPU via the axon tunnel),
     bounded retries with backoff — each attempt SIGTERM'd then SIGKILL'd on
     timeout (a wedged backend ignores SIGTERM);
  2. on failure, a CPU fallback at a reduced, clearly-labeled config
     (JAX_PLATFORMS=cpu with the axon relay env stripped, so a wedged tunnel
     can't hang interpreter start);
  3. if even that fails, a value-0 line with the error — still rc=0.

The reference publishes no benchmark numbers (BASELINE.md — its matching
core is an empty file and its hot path is one SQLite INSERT under a global
mutex), so vs_baseline is measured against this repo's north-star target of
10M orders/sec (BASELINE.json). Method + checked-in artifacts:
docs/BENCH_METHOD.md.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

NORTH_STAR = 10_000_000  # orders/sec, BASELINE.json
REPO = os.path.dirname(os.path.abspath(__file__))
CHILD = os.path.join(REPO, "benchmarks", "bench_child.py")

WALL_BUDGET_S = float(os.environ.get("BENCH_WALL_BUDGET_S", 480))
TPU_ATTEMPT_TIMEOUT_S = float(os.environ.get("BENCH_TPU_TIMEOUT_S", 300))
TPU_ATTEMPTS = int(os.environ.get("BENCH_TPU_ATTEMPTS", 2))
CPU_RESERVE_S = 150.0  # wall-clock kept aside for the CPU fallback
RETRY_BACKOFF_S = 10.0

# North-star config (BASELINE.json): 4k symbols; batch 32 amortizes dispatch
# overhead over a longer in-kernel scan. The CPU fallback runs the same
# kernel at the suite's reduced config-3 size so it finishes inside budget.
TPU_ARGS = ["--symbols", "4096", "--capacity", "128", "--batch", "32"]
CPU_ARGS = ["--symbols", "512", "--capacity", "128", "--batch", "32",
            "--windows", "3", "--iters", "5"]


def run_child(extra_env: dict, args: list, timeout_s: float):
    """Run one bench_child with a hard kill deadline.

    Returns (result_dict | None, error | None). Timeout escalates
    SIGTERM -> SIGKILL: a child stuck in a wedged backend init never
    handles SIGTERM.
    """
    fd, out_path = tempfile.mkstemp(suffix=".json", prefix="bench_")
    os.close(fd)
    env = dict(os.environ)
    env.update(extra_env)
    proc = subprocess.Popen(
        [sys.executable, CHILD, "--json-out", out_path, *args],
        env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        try:
            _, stderr = proc.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            proc.terminate()
            try:
                proc.communicate(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                try:
                    proc.communicate(timeout=10)
                except subprocess.TimeoutExpired:
                    pass  # unkillable (wedged in D-state): abandon it
            # The wedge can strike in backend TEARDOWN, after the
            # measurement was written — salvage it rather than fall back.
            try:
                with open(out_path) as f:
                    return json.load(f), None
            except (OSError, ValueError):
                pass
            return None, f"timeout after {timeout_s:.0f}s"
        if proc.returncode != 0:
            tail = " | ".join((stderr or "").strip().splitlines()[-3:])
            return None, f"rc={proc.returncode}: {tail[-500:]}"
        try:
            with open(out_path) as f:
                return json.load(f), None
        except (OSError, ValueError) as e:
            return None, f"child wrote no result: {e}"
    finally:
        try:
            os.unlink(out_path)
        except OSError:
            pass


def emit(value: float, extra: dict) -> None:
    line = {
        "metric": "match_throughput",
        "value": round(value, 1),
        "unit": "orders/sec",
        "vs_baseline": round(value / NORTH_STAR, 4),
    }
    line.update(extra)
    print(json.dumps(line), flush=True)


def main() -> None:
    deadline = time.monotonic() + WALL_BUDGET_S
    errors: list[str] = []

    for attempt in range(TPU_ATTEMPTS):
        # Attempt 1 gets the full attempt timeout: killing the child mid
        # cold-compile is what wedges the axon tunnel, so the orchestrator
        # must never convert a slow compile into a wedge. Only retries split
        # the remaining pre-reserve wall (a wedged init fails fast anyway).
        remaining = deadline - time.monotonic() - CPU_RESERVE_S
        if attempt == 0:
            budget = min(TPU_ATTEMPT_TIMEOUT_S, remaining)
        else:
            budget = min(TPU_ATTEMPT_TIMEOUT_S, remaining / (TPU_ATTEMPTS - attempt))
        if budget < min(60, TPU_ATTEMPT_TIMEOUT_S):
            errors.append("tpu attempts stopped: wall budget exhausted")
            break
        if attempt:
            time.sleep(min(RETRY_BACKOFF_S, max(0, deadline - time.monotonic() - CPU_RESERVE_S - 60)))
        result, err = run_child({}, TPU_ARGS, budget)
        if result is not None:
            emit(result.pop("value"), result)
            return
        errors.append(f"attempt {attempt + 1}: {err}")

    # CPU fallback — labeled, reduced config, axon relay env stripped so a
    # wedged tunnel can't hang interpreter start (sitecustomize registers
    # with the relay when PALLAS_AXON_POOL_IPS is set).
    env = {"JAX_PLATFORMS": "cpu"}
    budget = max(30.0, deadline - time.monotonic() - 5)
    saved = os.environ.get("PALLAS_AXON_POOL_IPS")
    if saved is not None:
        del os.environ["PALLAS_AXON_POOL_IPS"]
    try:
        result, err = run_child(env, CPU_ARGS, min(budget, 240.0))
    finally:
        if saved is not None:
            os.environ["PALLAS_AXON_POOL_IPS"] = saved
    tpu_error = "; ".join(errors) or "unknown"
    extra = {}
    artifact = latest_tpu_artifact()
    if artifact is not None:
        # The tunnel wedges for hours at a stretch; a watcher captured a
        # real-TPU figure during a healthy window earlier (BENCH_METHOD.md
        # artifact row). Point at it so this fallback line still carries
        # the hardware evidence.
        extra["last_tpu_artifact"] = artifact
    if result is not None:
        emit(result.pop("value"), {
            **result, **extra,
            "error": f"tpu unavailable, CPU-fallback figure: {tpu_error}",
        })
        return
    emit(0.0, {**extra, "error": f"tpu: {tpu_error}; cpu fallback: {err}"})


def latest_tpu_artifact():
    """NEWEST builder-captured real-TPU figure at the headline 4096-symbol
    condition under benchmarks/results/ — from the standalone tpu_*.json
    captures AND the suite .jsonl files' config-3 rows (the suite measures
    the same condition via the same measure_device_throughput) — plus the
    best value/file across all captures as separate fields (a regression
    must surface in the newest figure, not be hidden behind a stale peak).
    Falls back to the newest TPU capture at any config. None if nothing
    was captured."""
    root = os.path.join(REPO, "benchmarks", "results")
    candidates = []  # (symbols, value, row, name)
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return None
    for name in names:
        path = os.path.join(root, name)
        rows = []
        try:
            if name.startswith("tpu_") and name.endswith(".json"):
                with open(path) as f:
                    rows = [json.load(f)]
            elif name.startswith("tpu_suite") and name.endswith(".jsonl"):
                with open(path) as f:
                    rows = [json.loads(line) for line in f if line.strip()]
        except (OSError, ValueError):
            continue  # in-progress/corrupt capture: skip, keep older evidence
        for row in rows:
            if not (isinstance(row, dict)
                    and row.get("platform") in ("tpu", "axon")):
                continue
            if row.get("config") not in (None, 3):
                continue  # suite rows: only config 3 measures the headline
            if not isinstance(row.get("value"), (int, float)):
                continue
            candidates.append((row.get("symbols"), row["value"], row, name))
    if not candidates:
        return None
    headline = [c for c in candidates if c[0] == 4096]
    # Directory listing is ts-sorted, so the last candidate is the newest.
    _, value, row, name = (headline or candidates)[-1]
    out = {
        "file": f"benchmarks/results/{name}",
        "value": value,
        "symbols": row.get("symbols"),
        "mean_dispatch_latency_us": row.get("mean_dispatch_latency_us"),
    }
    if headline:
        _, best_value, _, best_name = max(headline, key=lambda c: c[1])
        out["best_value"] = best_value
        out["best_file"] = f"benchmarks/results/{best_name}"
    return out


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001 — one JSON line, rc 0, no matter what
        print(json.dumps({
            "metric": "match_throughput", "value": 0.0, "unit": "orders/sec",
            "vs_baseline": 0.0, "error": f"bench orchestrator: {type(e).__name__}: {e}",
        }))
    sys.exit(0)
