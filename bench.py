"""Benchmark: sustained match-engine throughput on the attached accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no benchmark numbers (BASELINE.md — its matching core
is an empty file and its hot path is one SQLite INSERT under a global mutex),
so vs_baseline is measured against this repo's north-star target of 10M
orders/sec (BASELINE.json) rather than a reference figure.

Method (utils/measure.py, shared with benchmarks/run_all.py): steady-state
device throughput of the jit'd engine step at the north-star condition — a
realistic mixed 4096-symbol stream (limit adds that rest, crossing limits,
markets, cancels) pre-built into [S, B] dispatches, run back-to-back with the
book donated in HBM; the median of post-warm-up fully-synced timing windows
is reported. orders/sec counts real (non-padding) ops.
"""

from __future__ import annotations

import json

from matching_engine_tpu.engine.book import EngineConfig
from matching_engine_tpu.engine.harness import random_order_stream
from matching_engine_tpu.utils.measure import measure_device_throughput

NORTH_STAR = 10_000_000  # orders/sec, BASELINE.json


def main() -> None:
    # North-star condition (BASELINE.json): 4k symbols. batch=32 amortizes the
    # per-step dispatch overhead over a longer in-kernel scan.
    cfg = EngineConfig(num_symbols=4096, capacity=128, batch=32, max_fills=1 << 17)
    streams = [
        random_order_stream(
            cfg.num_symbols, 4 * cfg.num_symbols * cfg.batch, seed=w, cancel_p=0.10,
            market_p=0.15, price_base=9_950, price_levels=100, price_step=1,
            qty_max=100,
        )
        for w in range(4)
    ]
    value, _lat_us = measure_device_throughput(cfg, streams)
    print(json.dumps({
        "metric": "match_throughput",
        "value": round(value, 1),
        "unit": "orders/sec",
        "vs_baseline": round(value / NORTH_STAR, 4),
    }))


if __name__ == "__main__":
    main()
