"""Benchmark: sustained match-engine throughput on the attached accelerator.

Prints exactly ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}
and always exits 0 — a wedged TPU tunnel must degrade the number, never the
driver run (round 1's bench died rc=1 on backend init and hung >9 min on a
rerun; this orchestrator is the fix).

Structure: this process never imports jax. The measurement runs in a child
(benchmarks/bench_child.py) whose wall-clock is bounded here:

  0. if a watcher-kept warm resident (benchmarks/resident.py) is alive with
     a fresh heartbeat, signal it — a compiled-engine measurement lands in
     seconds instead of paying init+compile inside the wall budget;
  1. a CHEAP backend probe (~25s subprocess doing jax.devices(); healthy
     init is sub-second, r3 artifacts) decides whether to spend the budget
     on a real attempt at all — round 3 burned its whole 300s on one
     wedged attempt (VERDICT r3 weak #1);
  2. on a healthy probe, a STAGED measurement child: a small config writes
     a salvageable real-TPU figure before the full 4k config overwrites it,
     so a timeout mid-full-run still yields hardware evidence;
  3. on failure, a CPU fallback at a reduced, clearly-labeled config
     (JAX_PLATFORMS=cpu with the axon relay env stripped, so a wedged tunnel
     can't hang interpreter start);
  4. if even that fails, a value-0 line with the error — still rc=0.

The reference publishes no benchmark numbers (BASELINE.md — its matching
core is an empty file and its hot path is one SQLite INSERT under a global
mutex), so vs_baseline is measured against this repo's north-star target of
10M orders/sec (BASELINE.json). Method + checked-in artifacts:
docs/BENCH_METHOD.md.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

NORTH_STAR = 10_000_000  # orders/sec, BASELINE.json
REPO = os.path.dirname(os.path.abspath(__file__))
CHILD = os.path.join(REPO, "benchmarks", "bench_child.py")

WALL_BUDGET_S = float(os.environ.get("BENCH_WALL_BUDGET_S", 480))
TPU_ATTEMPT_TIMEOUT_S = float(os.environ.get("BENCH_TPU_TIMEOUT_S", 300))
TPU_ATTEMPTS = int(os.environ.get("BENCH_TPU_ATTEMPTS", 2))
CPU_RESERVE_S = 120.0  # wall-clock kept aside for the CPU fallback
PROBE_TIMEOUT_S = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", 25))
PROBE_TRIES = 3
RETRY_BACKOFF_S = 10.0
RESIDENT_WAIT_S = float(os.environ.get("BENCH_RESIDENT_WAIT_S", 90))
RESIDENT_HEARTBEAT_FRESH_S = 120.0
RESIDENT_DIR = os.path.join(REPO, "benchmarks", ".resident")

# North-star config (BASELINE.json): 4k symbols; batch 32 amortizes dispatch
# overhead over a longer in-kernel scan. The headline formulation is the
# SORTED kernel — decided from hardware on 2026-07-31 (round-5 window):
# 2.21B orders/s vs the matrix kernel's 1.26B at this exact shape
# (tpu_r4_headline_sorted.json vs tpu_r4_headline.json; analysis in
# docs/DESIGN.md §6d). --stage-symbols writes a salvageable small-config
# TPU figure first. The CPU fallback runs a reduced config sized to
# finish inside budget.
TPU_ARGS = ["--symbols", "4096", "--capacity", "128", "--batch", "32",
            "--kernel", "sorted", "--stage-symbols", "512"]
# The headline config as key/value truth (single source for the resident
# handshake below — a resident warmed on any OTHER shape or formulation
# must not supply the headline record).
_TPU_FLAGS = dict(zip(TPU_ARGS[::2], TPU_ARGS[1::2]))
HEADLINE_CFG = {
    "symbols": int(_TPU_FLAGS["--symbols"]),
    "capacity": int(_TPU_FLAGS["--capacity"]),
    "batch": int(_TPU_FLAGS["--batch"]),
    "kernel": _TPU_FLAGS.get("--kernel", "matrix"),
}
# The CPU fallback uses the sorted-book kernel: 3.7x the matrix kernel's
# throughput on the host backend at this config (63.4k vs 17.1k orders/s
# measured 2026-07-30) — the row carries its kernel label.
CPU_ARGS = ["--symbols", "512", "--capacity", "128", "--batch", "32",
            "--windows", "3", "--iters", "5", "--kernel", "sorted"]


def run_child(extra_env: dict, args: list, timeout_s: float):
    """Run one bench_child with a hard kill deadline.

    Returns (result_dict | None, error | None). Timeout escalates
    SIGTERM -> SIGKILL: a child stuck in a wedged backend init never
    handles SIGTERM.
    """
    fd, out_path = tempfile.mkstemp(suffix=".json", prefix="bench_")
    os.close(fd)
    env = dict(os.environ)
    env.update(extra_env)
    proc = subprocess.Popen(
        [sys.executable, CHILD, "--json-out", out_path, *args],
        env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        try:
            _, stderr = proc.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            proc.terminate()
            try:
                proc.communicate(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                try:
                    proc.communicate(timeout=10)
                except subprocess.TimeoutExpired:
                    pass  # unkillable (wedged in D-state): abandon it
            # The wedge can strike in backend TEARDOWN, after the
            # measurement was written — salvage it rather than fall back.
            # Annotated like the crash path: a salvaged small-stage row
            # must carry the signal that the full-config attempt died.
            try:
                with open(out_path) as f:
                    result = json.load(f)
                result["child_error"] = f"timeout after {timeout_s:.0f}s"
                return result, None
            except (OSError, ValueError):
                pass
            return None, f"timeout after {timeout_s:.0f}s"
        if proc.returncode != 0:
            # Same salvage as the timeout path: a staged child that crashed
            # in the FULL config already wrote its small-config real-TPU
            # row atomically — a crash must not discard it for a CPU
            # fallback.
            tail = " | ".join((stderr or "").strip().splitlines()[-3:])
            err = f"rc={proc.returncode}: {tail[-500:]}"
            try:
                with open(out_path) as f:
                    result = json.load(f)
                result["child_error"] = err
                return result, None
            except (OSError, ValueError):
                pass
            return None, err
        try:
            with open(out_path) as f:
                return json.load(f), None
        except (OSError, ValueError) as e:
            return None, f"child wrote no result: {e}"
    finally:
        try:
            os.unlink(out_path)
        except OSError:
            pass


def emit(value: float, extra: dict) -> None:
    line = {
        "metric": "match_throughput",
        "value": round(value, 1),
        "unit": "orders/sec",
        "vs_baseline": round(value / NORTH_STAR, 4),
    }
    line.update(extra)
    print(json.dumps(line), flush=True)


def probe_backend(timeout_s: float) -> tuple[bool, str]:
    """Cheap tunnel-health probe: a subprocess that just inits the backend.
    Healthy init is sub-second (r3 artifacts: backend_init_s 0.1-0.4);
    wedged it hangs until killed. SIGKILL directly — a wedged init never
    handles SIGTERM, and the probe has no state worth draining."""
    proc = subprocess.Popen(
        [sys.executable, "-c", "import jax; assert jax.devices()"],
        cwd=REPO, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        text=True,
    )
    try:
        _, stderr = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.kill()
        try:
            proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            pass
        return False, f"probe timeout after {timeout_s:.0f}s"
    if proc.returncode != 0:
        tail = " | ".join((stderr or "").strip().splitlines()[-2:])
        return False, f"probe rc={proc.returncode}: {tail[-200:]}"
    return True, ""


def try_resident(deadline: float, errors: list[str]):
    """Phase 0: a warm resident with a fresh heartbeat serves a measured
    row in seconds. Returns the row dict or None (reason appended)."""
    state_path = os.path.join(RESIDENT_DIR, "state.json")
    try:
        with open(state_path) as f:
            state = json.load(f)
    except (OSError, ValueError):
        return None  # no resident — normal when no watcher ran; not an error
    age = time.time() - state.get("heartbeat_ts", 0)
    if age > RESIDENT_HEARTBEAT_FRESH_S:
        errors.append(f"resident heartbeat stale ({age:.0f}s)")
        return None
    try:
        os.kill(int(state["pid"]), 0)
    except (OSError, KeyError, ValueError):
        errors.append("resident pid dead")
        return None
    mismatch = {
        k: (state.get(k, "matrix" if k == "kernel" else None), want)
        for k, want in HEADLINE_CFG.items()
        if state.get(k, "matrix" if k == "kernel" else None) != want
    }
    if mismatch:
        # A resident warmed on another shape or formulation must not
        # supply the headline record; fall through to the staged child
        # rather than mislabel the row.
        errors.append(f"resident config mismatch {mismatch}")
        return None
    nonce = f"{os.getpid()}-{int(time.time())}"
    out_path = os.path.join(RESIDENT_DIR, f"out-{nonce}.json")
    try:
        with open(os.path.join(RESIDENT_DIR, f"req-{nonce}"), "w") as f:
            f.write("")
    except OSError as e:
        errors.append(f"resident request write failed: {e}")
        return None
    wait_until = min(time.monotonic() + RESIDENT_WAIT_S,
                     deadline - CPU_RESERVE_S)
    while time.monotonic() < wait_until:
        if os.path.exists(out_path):
            try:
                with open(out_path) as f:
                    row = json.load(f)
            except (OSError, ValueError):
                row = None  # mid-write; next poll reads the atomic replace
            if row is not None:
                try:
                    os.unlink(out_path)
                except OSError:
                    pass
                if "error" in row or "value" not in row:
                    errors.append(
                        f"resident measure failed: {row.get('error')}")
                    return None
                return row
        time.sleep(0.5)
    errors.append(f"resident did not answer within {RESIDENT_WAIT_S:.0f}s")
    return None


def main() -> None:
    deadline = time.monotonic() + WALL_BUDGET_S
    errors: list[str] = []

    # Phase 0: warm resident (watcher-kept compiled engine).
    result = try_resident(deadline, errors)
    if result is not None:
        emit(result.pop("value"), result)
        return

    # Phases 1+2: probe, then a staged measurement child per healthy probe.
    # A wedged tunnel now costs ~3 cheap probes (~75s) instead of one
    # 300s attempt; a healthy one gets the whole pre-reserve budget.
    probes_left = PROBE_TRIES
    attempts_left = TPU_ATTEMPTS
    while probes_left > 0 and attempts_left > 0:
        remaining = deadline - time.monotonic() - CPU_RESERVE_S
        if remaining < PROBE_TIMEOUT_S + 30:
            errors.append("tpu attempts stopped: wall budget exhausted")
            break
        ok, perr = probe_backend(min(PROBE_TIMEOUT_S, remaining - 10))
        if not ok:
            probes_left -= 1
            errors.append(perr)
            if probes_left > 0:
                # A fast-failing probe (relay restarting: connection
                # refused in ~2s) must not burn all tries in seconds —
                # ride out the transient, bounded by the budget.
                time.sleep(min(RETRY_BACKOFF_S, max(
                    0, deadline - time.monotonic() - CPU_RESERVE_S - 60)))
            continue
        remaining = deadline - time.monotonic() - CPU_RESERVE_S
        budget = min(TPU_ATTEMPT_TIMEOUT_S, remaining)
        if budget < min(60, TPU_ATTEMPT_TIMEOUT_S):
            errors.append("tpu attempts stopped: wall budget exhausted")
            break
        attempts_left -= 1
        result, err = run_child({}, TPU_ARGS, budget)
        if result is not None:
            emit(result.pop("value"), result)
            return
        errors.append(f"attempt {TPU_ATTEMPTS - attempts_left}: {err}")

    # CPU fallback — labeled, reduced config, axon relay env stripped so a
    # wedged tunnel can't hang interpreter start (sitecustomize registers
    # with the relay when PALLAS_AXON_POOL_IPS is set).
    env = {"JAX_PLATFORMS": "cpu"}
    budget = max(30.0, deadline - time.monotonic() - 5)
    saved = os.environ.get("PALLAS_AXON_POOL_IPS")
    if saved is not None:
        del os.environ["PALLAS_AXON_POOL_IPS"]
    try:
        result, err = run_child(env, CPU_ARGS, min(budget, 240.0))
    finally:
        if saved is not None:
            os.environ["PALLAS_AXON_POOL_IPS"] = saved
    tpu_error = "; ".join(errors) or "unknown"
    extra = {}
    artifact = latest_tpu_artifact()
    if artifact is not None:
        # The tunnel wedges for hours at a stretch; a watcher captured a
        # real-TPU figure during a healthy window earlier (BENCH_METHOD.md
        # artifact row). Point at it so this fallback line still carries
        # the hardware evidence.
        extra["last_tpu_artifact"] = artifact
    if result is not None:
        emit(result.pop("value"), {
            **result, **extra,
            "error": f"tpu unavailable, CPU-fallback figure: {tpu_error}",
        })
        return
    emit(0.0, {**extra, "error": f"tpu: {tpu_error}; cpu fallback: {err}"})


def latest_tpu_artifact():
    """NEWEST builder-captured real-TPU figure at the headline 4096-symbol
    condition under benchmarks/results/ — from the standalone tpu_*.json
    captures AND the suite .jsonl files' config-3 rows (the suite measures
    the same condition via the same measure_device_throughput) — plus the
    best value/file across all captures as separate fields (a regression
    must surface in the newest figure, not be hidden behind a stale peak).
    Falls back to the newest TPU capture at any config. None if nothing
    was captured."""
    root = os.path.join(REPO, "benchmarks", "results")
    candidates = []  # (symbols, value, row, name)
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return None
    for name in names:
        path = os.path.join(root, name)
        rows = []
        try:
            if name.startswith("tpu_") and name.endswith(".json"):
                with open(path) as f:
                    rows = [json.load(f)]
            elif name.startswith("tpu_suite") and name.endswith(".jsonl"):
                with open(path) as f:
                    rows = [json.loads(line) for line in f if line.strip()]
            elif name == "tpu_resident_log.jsonl":
                # The warm resident's measurement log: headline-config
                # real-TPU rows, often the freshest evidence on disk.
                with open(path) as f:
                    rows = [json.loads(line) for line in f if line.strip()]
        except (OSError, ValueError):
            continue  # in-progress/corrupt capture: skip, keep older evidence
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            mtime = 0.0
        for i, row in enumerate(rows):
            if not (isinstance(row, dict)
                    and row.get("platform") in ("tpu", "axon")):
                continue
            if row.get("config") not in (None, 3):
                continue  # suite rows: only config 3 measures the headline
            if not isinstance(row.get("value"), (int, float)):
                continue
            candidates.append(
                ((mtime, i), row.get("symbols"), row["value"], row, name))
    if not candidates:
        return None
    headline = [c for c in candidates if c[1] == 4096]
    # Newest by file mtime (append-logs keep getting fresher rows without
    # a fresher NAME, so listing order alone is not recency), then by
    # in-file position.
    _, _, value, row, name = max(headline or candidates, key=lambda c: c[0])
    out = {
        "file": f"benchmarks/results/{name}",
        "value": value,
        "symbols": row.get("symbols"),
        "mean_dispatch_latency_us": row.get("mean_dispatch_latency_us"),
    }
    if headline:
        _, _, best_value, _, best_name = max(headline, key=lambda c: c[2])
        out["best_value"] = best_value
        out["best_file"] = f"benchmarks/results/{best_name}"
    return out


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001 — one JSON line, rc 0, no matter what
        print(json.dumps({
            "metric": "match_throughput", "value": 0.0, "unit": "orders/sec",
            "vs_baseline": 0.0, "error": f"bench orchestrator: {type(e).__name__}: {e}",
        }))
    sys.exit(0)
